"""graftlint's project-wide indexing pass (the two-phase engine).

Phase one of the two-phase analysis: before any :class:`ProjectRule`
runs, every parsed file is folded into a :class:`ProjectIndex` holding

* a **symbol table** — classes (with their mixin-composition groups,
  resolved through base-class names: ``InferenceEngine(SchedulerMixin,
  ...)`` composes into ONE runtime object, so its locks and attributes
  are modeled per *group*, not per class), methods, module functions;
* a **call graph** — ``self.m()`` resolves within the composition
  group, bare names resolve to module functions (or sibling nested
  defs), and ``obj.m()`` resolves only when exactly one indexed class
  defines ``m`` (unique-name resolution: sound enough for edges, too
  conservative to invent false ones);
* a **lock model** — every ``threading.Lock/RLock/Condition`` (or
  ``lockcheck.make_lock``) attribute, the ``with self._lock:`` regions
  that acquire it, manual ``release()``/``acquire()`` windows *inside*
  those regions (the PR 4 release-around-adoption shape), and every
  attribute read/write annotated with the set of locks lexically held;
* **thread roots** — functions handed to ``threading.Thread(target=…)``
  plus a synthetic ``caller`` root covering the public entry points the
  HTTP/request threads call into.

Phase two (``rules.py``'s GL020–GL022) consumes the index; the runner
in ``core.py`` builds it once per invocation.

Lock identity is the pair *(composition group, attribute name)* so the
engine's ``_submit_lock`` is one lock however many mixins mention it,
while unrelated classes' ``_lock`` attributes stay distinct. A foreign
``obj._submit_lock`` acquisition (the supervisor's idiom) resolves when
exactly one group defines a lock attribute of that name.

Guarded-by declarations bind an attribute to its lock explicitly::

    self._epoch = 0  # graftlint: guarded-by=_submit_lock

and take precedence over GL020's majority-access inference.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

from gofr_tpu.analysis.core import FileContext

_GUARDED_BY_RE = re.compile(r"#\s*graftlint:\s*guarded-by\s*=\s*(\w+)")

#: Callables whose result is a lock object (attribute leaf names).
_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "make_lock"))

#: Identifier substrings that mark a lock-ish attribute even without a
#: visible constructor (annotations, injected locks) — the GL005 idiom.
_LOCKISH = ("lock", "cond", "mutex")

#: The synthetic thread root modeling request/caller threads: every
#: public (non-underscore) function is an entry point for it.
CALLER_ROOT = "caller"

#: Blocking primitives for GL022 / the lock-model's blocking sets.
#: Fully-dotted names match exactly; leaf names match any receiver.
BLOCKING_CALLS = frozenset((
    "time.sleep",
    "requests.get", "requests.post", "requests.put", "requests.patch",
    "requests.delete", "requests.head", "requests.request",
    "urllib.request.urlopen",
    "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
))
BLOCKING_LEAVES = frozenset(("block_until_ready", "device_get"))
#: Leaves that only block when the receiver looks like a thread.
_JOIN_LEAF = "join"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None (local copy so
    the index has no import cycle with rules.py)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ----------------------------------------------------------------------
# lock-region extraction (shared with GL005's per-file check)
# ----------------------------------------------------------------------


@dataclass
class LockRegion:
    """One ``with <lock>:`` block: the lock expression's dotted name,
    its line span, and any manual release windows inside it.

    A release window is the span between ``<lock>.release()`` and the
    next ``<lock>.acquire()`` (or the region's end): code there runs
    with the lock **dropped**, however lexically nested it is — the
    exact shape PR 4's release-around-adoption seam used, and the shape
    GL005 historically mis-classified as guarded (lock-free writes in
    the ``except``/``finally`` of the released window were invisible).
    """

    lock_expr: str  # dotted source expression, e.g. "self._submit_lock"
    lineno: int
    end_lineno: int
    release_windows: list[tuple[int, int]] = field(default_factory=list)

    def holds_at(self, line: int) -> bool:
        """Is the lock actually held at ``line`` (lexically inside the
        region and not inside a manual release window)?"""
        if not (self.lineno <= line <= self.end_lineno):
            return False
        return not any(lo < line < hi for lo, hi in self.release_windows)


def _is_lockish_expr(expr: ast.AST) -> Optional[str]:
    """The dotted name of a with-item that acquires a lock, else None.
    ``with self._lock:`` and ``with self._lock.acquire_timeout(..)``-
    style calls both count when the name mentions a lock."""
    name = dotted_name(expr)
    if name is None and isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1].lower()
    if any(marker in leaf for marker in _LOCKISH):
        return name
    return None


def lock_regions(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> list[LockRegion]:
    """Every with-lock region in ``fn``'s own body (nested defs
    excluded — a closure runs on its own schedule), with manual
    ``release()``/``acquire()`` windows subtracted."""
    regions: list[LockRegion] = []
    for node in _walk_own(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            name = _is_lockish_expr(item.context_expr)
            if name is None:
                continue
            region = LockRegion(
                lock_expr=name,
                lineno=node.lineno,
                end_lineno=node.end_lineno or node.lineno,
            )
            _collect_release_windows(node, name, region)
            regions.append(region)
            break
    return regions


def _collect_release_windows(
    with_node: ast.AST, lock_name: str, region: LockRegion
) -> None:
    """Fill ``region.release_windows`` from ``<lock>.release()`` /
    ``<lock>.acquire()`` calls lexically inside ``with_node``."""
    events: list[tuple[int, str]] = []
    for node in ast.walk(with_node):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr not in ("release", "acquire"):
            continue
        if dotted_name(node.func.value) != lock_name:
            continue
        events.append((node.lineno, node.func.attr))
    events.sort()
    open_at: Optional[int] = None
    for line, kind in events:
        if kind == "release" and open_at is None:
            open_at = line
        elif kind == "acquire" and open_at is not None:
            region.release_windows.append((open_at, line))
            open_at = None
    if open_at is not None:
        region.release_windows.append((open_at, region.end_lineno + 1))


def _walk_own(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk ``fn``'s body without descending into nested function/
    class bodies (separate scopes, separate schedules)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ----------------------------------------------------------------------
# index records
# ----------------------------------------------------------------------


@dataclass
class LockDef:
    """One lock object: ``key`` is ``<group>.<attr>`` for instance
    locks, ``<path>:<name>`` for module-level locks."""

    key: str
    attr: str
    owner: str  # composition-group leader class name, or module path
    kind: str  # "Lock" | "RLock" | "Condition" | "make_lock" | "decl"
    path: str
    line: int


@dataclass
class Acquisition:
    """One static acquisition site of ``lock`` inside ``func``."""

    lock: str  # lock key
    path: str
    line: int
    col: int
    func: str  # function key


@dataclass
class CallSite:
    """One call edge candidate: ``callee`` is the resolved function
    key (None when resolution failed), ``name`` the source spelling."""

    name: str
    callee: Optional[str]
    path: str
    line: int
    col: int
    locks_held: frozenset[str] = frozenset()


@dataclass
class AttrAccess:
    """One ``self.<attr>`` read/write with the lock set lexically held
    at that line (release windows already subtracted)."""

    attr: str  # bare attribute name
    group: str  # composition-group leader
    write: bool
    path: str
    line: int
    col: int
    func: str  # function key
    locks_held: frozenset[str] = frozenset()
    in_init: bool = False


@dataclass
class FunctionInfo:
    """One function/method (nested defs get their own entry)."""

    key: str  # "<path>::<Class>.<name>" / "<path>::<name>" (+ ".<nested>")
    name: str
    path: str
    line: int
    group: Optional[str]  # composition-group leader for methods
    is_public: bool
    calls: list[CallSite] = field(default_factory=list)
    acquisitions: list[Acquisition] = field(default_factory=list)
    regions: list[tuple[str, LockRegion]] = field(default_factory=list)
    accesses: list[AttrAccess] = field(default_factory=list)
    blocking: list[tuple[str, int, int]] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    path: str
    line: int
    bases: tuple[str, ...]
    methods: dict[str, str] = field(default_factory=dict)  # name -> key
    lock_attrs: dict[str, LockDef] = field(default_factory=dict)
    guarded_by: dict[str, str] = field(default_factory=dict)  # attr -> lock attr


# ----------------------------------------------------------------------
# the index
# ----------------------------------------------------------------------


class ProjectIndex:
    """The cross-file model GL020–GL022 run against. Build once per
    lint invocation via :meth:`build`."""

    def __init__(self) -> None:
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}  # bare class name -> info
        self.locks: dict[str, LockDef] = {}
        self.files: dict[str, FileContext] = {}
        #: thread roots: function key -> human label
        self.thread_roots: dict[str, str] = {}
        #: group leader -> member class names
        self.groups: dict[str, set[str]] = {}
        #: (group, attr) -> lock key, from guarded-by declarations
        self.guarded_by: dict[tuple[str, str], str] = {}
        # memos
        self._roots_of: Optional[dict[str, frozenset[str]]] = None
        self._entry_locks: Optional[dict[str, frozenset[str]]] = None
        self._may_acquire: dict[str, dict[str, tuple[str, ...]]] = {}
        self._may_block: dict[str, dict[str, tuple[str, ...]]] = {}
        # resolution helpers (built in _finish)
        self._group_of_class: dict[str, str] = {}
        self._group_methods: dict[str, dict[str, str]] = {}
        self._unique_methods: dict[str, Optional[str]] = {}
        self._unique_lock_attr: dict[str, Optional[str]] = {}
        self._module_funcs: dict[str, dict[str, str]] = {}
        self._module_locks: dict[str, dict[str, str]] = {}
        self._file_imports: dict[str, set[str]] = {}

    # -- construction --------------------------------------------------

    @classmethod
    def build(
        cls, files: Sequence[tuple[FileContext, ast.Module]]
    ) -> "ProjectIndex":
        index = cls()
        # Pass 1: classes, composition groups, lock defs, module funcs.
        for ctx, tree in files:
            index.files[ctx.path] = ctx
            index._index_symbols(ctx, tree)
        index._build_groups()
        for ctx, tree in files:
            index._index_lock_defs(ctx, tree)
        index._finish_resolution()
        # Pass 2: per-function bodies (needs lock keys + groups).
        for ctx, tree in files:
            index._index_bodies(ctx, tree)
        index._discover_thread_roots()
        return index

    def _index_symbols(self, ctx: FileContext, tree: ast.Module) -> None:
        module_funcs: dict[str, str] = {}
        # Names bound by imports (anywhere in the file, incl. function-
        # local imports): a call through one of these is a call into a
        # library, and must never resolve to a same-named repo method.
        imported: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imported.add(alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    imported.add(alias.asname or alias.name)
        self._file_imports[ctx.path] = imported
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                bases = tuple(
                    n for b in node.bases for n in [dotted_name(b)]
                    if n is not None
                )
                info = ClassInfo(
                    name=node.name, path=ctx.path, line=node.lineno,
                    bases=tuple(b.rsplit(".", 1)[-1] for b in bases),
                )
                for stmt in node.body:
                    if isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        key = f"{ctx.path}::{node.name}.{stmt.name}"
                        info.methods[stmt.name] = key
                # Last definition wins on bare-name collisions; the
                # colliding earlier class stays in groups but loses
                # name-based resolution (conservative: fewer edges).
                self.classes[node.name] = info
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_funcs[node.name] = f"{ctx.path}::{node.name}"
        self._module_funcs[ctx.path] = module_funcs

    def _build_groups(self) -> None:
        """Union classes with their (indexed) bases: mixins over one
        runtime object share locks and attributes."""
        parent: dict[str, str] = {c: c for c in self.classes}

        def find(x: str) -> str:
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        def union(a: str, b: str) -> None:
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb

        for name, info in self.classes.items():
            for base in info.bases:
                if base in self.classes:
                    union(name, base)
        for name in self.classes:
            leader = find(name)
            self.groups.setdefault(leader, set()).add(name)
            self._group_of_class[name] = leader
        for leader, members in self.groups.items():
            methods: dict[str, str] = {}
            # Base-first so derived definitions override.
            for member in sorted(
                members, key=lambda m: len(self.classes[m].bases)
            ):
                methods.update(self.classes[member].methods)
            self._group_methods[leader] = methods

    def _index_lock_defs(self, ctx: FileContext, tree: ast.Module) -> None:
        for node in tree.body:
            # Module-level locks: X = threading.Lock()
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                kind = self._lock_ctor_kind(node.value)
                if kind is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            key = f"{ctx.path}:{tgt.id}"
                            self.locks[key] = LockDef(
                                key=key, attr=tgt.id, owner=ctx.path,
                                kind=kind, path=ctx.path,
                                line=node.lineno,
                            )
            if not isinstance(node, ast.ClassDef):
                continue
            info = self.classes.get(node.name)
            if info is None or info.path != ctx.path:
                continue
            group = self._group_of_class[node.name]
            for stmt in ast.walk(node):
                # self.X = threading.Lock() / lockcheck.make_lock(...)
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ):
                    kind = self._lock_ctor_kind(stmt.value)
                    if kind is None:
                        continue
                    for tgt in stmt.targets:
                        attr = self._self_attr(tgt)
                        if attr is not None:
                            self._add_lock(
                                group, attr, kind, ctx.path, stmt.lineno
                            )
                # class-level annotation: _submit_lock: threading.Lock
                elif isinstance(stmt, ast.AnnAssign):
                    ann = dotted_name(stmt.annotation) or ""
                    leaf = ann.rsplit(".", 1)[-1]
                    if leaf in ("Lock", "RLock", "Condition"):
                        attr = None
                        if isinstance(stmt.target, ast.Name):
                            attr = stmt.target.id
                        else:
                            attr = self._self_attr(stmt.target)
                        if attr is not None:
                            self._add_lock(
                                group, attr, "decl", ctx.path, stmt.lineno
                            )
            # guarded-by declarations anywhere in the class body.
            lo = node.lineno
            hi = node.end_lineno or node.lineno
            for i in range(lo, min(hi, len(ctx.lines)) + 1):
                m = _GUARDED_BY_RE.search(ctx.lines[i - 1])
                if not m:
                    continue
                attr = self._decl_target_attr(node, i)
                if attr is not None:
                    info.guarded_by[attr] = m.group(1)

    def _add_lock(
        self, group: str, attr: str, kind: str, path: str, line: int
    ) -> None:
        key = f"{group}.{attr}"
        existing = self.locks.get(key)
        # A real constructor beats a bare annotation.
        if existing is not None and existing.kind != "decl":
            return
        self.locks[key] = LockDef(
            key=key, attr=attr, owner=group, kind=kind, path=path,
            line=line,
        )

    @staticmethod
    def _decl_target_attr(cls_node: ast.ClassDef, line: int) -> Optional[str]:
        """The ``self.<attr>`` assigned on ``line`` (a guarded-by
        comment binds to its own statement's target)."""
        for stmt in ast.walk(cls_node):
            if stmt_line := getattr(stmt, "lineno", None):
                if stmt_line != line:
                    continue
                targets: list[ast.AST] = []
                if isinstance(stmt, ast.Assign):
                    targets = list(stmt.targets)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    targets = [stmt.target]
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        return tgt.attr
        return None

    @staticmethod
    def _lock_ctor_kind(call: ast.Call) -> Optional[str]:
        name = dotted_name(call.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _LOCK_CTORS:
            # threading.Condition(lock) wraps an existing lock; still a
            # lock-ish object from the model's perspective.
            return leaf
        return None

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _finish_resolution(self) -> None:
        # Unique method-name map for obj.m() resolution.
        seen: dict[str, Optional[str]] = {}
        for leader, methods in self._group_methods.items():
            for mname, key in methods.items():
                if mname in seen:
                    seen[mname] = None  # ambiguous
                else:
                    seen[mname] = key
        self._unique_methods = seen
        # Unique lock-attr map for foreign obj._submit_lock resolution.
        lock_attr_owner: dict[str, Optional[str]] = {}
        for lock in self.locks.values():
            if ":" in lock.key:
                continue  # module-level
            if lock.attr in lock_attr_owner:
                lock_attr_owner[lock.attr] = None
            else:
                lock_attr_owner[lock.attr] = lock.key
        self._unique_lock_attr = lock_attr_owner
        # guarded-by: resolve declared lock names to lock keys.
        for cname, info in self.classes.items():
            group = self._group_of_class[cname]
            for attr, lock_attr in info.guarded_by.items():
                key = self._resolve_lock_key(group, lock_attr)
                if key is not None:
                    self.guarded_by[(group, attr)] = key
        # module-level lock name maps per file.
        for key, lock in self.locks.items():
            if ":" in key:
                self._module_locks.setdefault(lock.path, {})[lock.attr] = key

    def _resolve_lock_key(
        self, group: Optional[str], attr: str
    ) -> Optional[str]:
        if group is not None and f"{group}.{attr}" in self.locks:
            return f"{group}.{attr}"
        return self._unique_lock_attr.get(attr) or None

    # -- body indexing -------------------------------------------------

    def _index_bodies(self, ctx: FileContext, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                group = self._group_of_class.get(node.name)
                for stmt in node.body:
                    if isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        key = f"{ctx.path}::{node.name}.{stmt.name}"
                        self._index_function(ctx, stmt, key, group)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{ctx.path}::{node.name}"
                self._index_function(ctx, node, key, None)

    def _index_function(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
        key: str,
        group: Optional[str],
    ) -> None:
        info = FunctionInfo(
            key=key, name=fn.name, path=ctx.path, line=fn.lineno,
            group=group, is_public=not fn.name.startswith("_"),
        )
        self.functions[key] = info
        # Lock regions (with release windows), resolved to lock keys
        # where possible; unresolved lock expressions still participate
        # under a synthetic per-expression key so discipline checks see
        # them.
        regions: list[tuple[str, LockRegion]] = []
        for region in lock_regions(fn):
            lock_key = self._region_lock_key(ctx, group, region.lock_expr)
            regions.append((lock_key, region))
            info.regions.append((lock_key, region))
            info.acquisitions.append(
                Acquisition(
                    lock=lock_key, path=ctx.path, line=region.lineno,
                    col=0, func=key,
                )
            )

        def held_at(line: int) -> frozenset[str]:
            return frozenset(
                lk for lk, region in regions if region.holds_at(line)
            )

        nested: dict[str, str] = {}
        for node in _walk_own(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nkey = f"{key}.{node.name}"
                nested[node.name] = nkey
                self._index_function(ctx, node, nkey, group)
                continue
            if isinstance(node, ast.Call):
                self._index_call(ctx, info, node, group, nested, held_at)
            self._index_access(ctx, info, node, group, fn.name, held_at)

    def _region_lock_key(
        self, ctx: FileContext, group: Optional[str], lock_expr: str
    ) -> str:
        parts = lock_expr.split(".")
        if parts[0] == "self" and len(parts) == 2 and group is not None:
            resolved = self._resolve_lock_key(group, parts[1])
            if resolved is not None:
                return resolved
            return f"{group}.{parts[1]}"
        if len(parts) == 1:
            mod = self._module_locks.get(ctx.path, {})
            if parts[0] in mod:
                return mod[parts[0]]
            return f"{ctx.path}:{parts[0]}"
        # foreign object: eng._submit_lock — unique-attr resolution.
        resolved = self._unique_lock_attr.get(parts[-1])
        if resolved:
            return resolved
        return f"?.{parts[-1]}"

    def _index_call(
        self,
        ctx: FileContext,
        info: FunctionInfo,
        node: ast.Call,
        group: Optional[str],
        nested: dict[str, str],
        held_at: "_HeldAt",
    ) -> None:
        name = dotted_name(node.func)
        if name is None:
            return
        held = held_at(node.lineno)
        parts = name.split(".")
        leaf = parts[-1]
        # blocking primitives
        if name in BLOCKING_CALLS or leaf in BLOCKING_LEAVES:
            info.blocking.append((name, node.lineno, node.col_offset))
        elif leaf == _JOIN_LEAF and len(parts) >= 2 and (
            "thread" in parts[-2].lower() or "_sched" in parts[-2].lower()
        ):
            info.blocking.append((name, node.lineno, node.col_offset))
        elif (
            leaf == "get"
            and len(parts) >= 2
            and self._queue_receiver(parts[-2])
            and not any(
                isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords if kw.arg == "block"
            )
        ):
            # queue.get() blocks unless block=False; get_nowait never.
            info.blocking.append((name, node.lineno, node.col_offset))
        # manual lock.acquire() outside a with — an acquisition event
        # (blocking-acquire order edges; `with` regions are collected
        # separately in lock_regions()).
        if leaf == "acquire" and len(parts) >= 2 and any(
            marker in parts[-2].lower() for marker in _LOCKISH
        ):
            lock_key = self._region_lock_key(
                ctx, group, ".".join(parts[:-1])
            )
            already = any(
                r.lineno <= node.lineno <= r.end_lineno
                for lk, r in info.regions if lk == lock_key
            )
            if not already:
                info.acquisitions.append(
                    Acquisition(
                        lock=lock_key, path=ctx.path, line=node.lineno,
                        col=node.col_offset, func=info.key,
                    )
                )
        callee = self._resolve_call(ctx, group, nested, parts)
        info.calls.append(
            CallSite(
                name=name, callee=callee, path=ctx.path,
                line=node.lineno, col=node.col_offset, locks_held=held,
            )
        )

    @staticmethod
    def _queue_receiver(name: str) -> bool:
        """Does ``name`` denote a queue object (whose ``.get`` blocks)?
        Exact-word matching only: ``self._tenant_queued.get(k, 0)`` is a
        dict counter, not a queue, and must not count."""
        low = name.lower()
        return (
            low in ("queue", "q")
            or low.endswith("_queue")
            or low.endswith("_q")
        )

    def _resolve_call(
        self,
        ctx: FileContext,
        group: Optional[str],
        nested: dict[str, str],
        parts: list[str],
    ) -> Optional[str]:
        if len(parts) == 1:
            if parts[0] in nested:
                return nested[parts[0]]
            return self._module_funcs.get(ctx.path, {}).get(parts[0])
        if parts[0] == "self" and len(parts) == 2 and group is not None:
            target = self._group_methods.get(group, {}).get(parts[1])
            if target is not None:
                return target
        if len(parts) == 2 and parts[0] in self.classes:
            # Klass.method(self, ...) — explicit class dispatch.
            return self.classes[parts[0]].methods.get(parts[1])
        if parts[0] in self._file_imports.get(ctx.path, ()):
            # os.path.exists / np.asarray / requests.get — a library
            # call, however its leaf happens to collide with a method
            # name somewhere in the repo.
            return None
        # obj.m(...) — unique-name resolution across indexed classes.
        return self._unique_methods.get(parts[-1]) or None

    def _index_access(
        self,
        ctx: FileContext,
        info: FunctionInfo,
        node: ast.AST,
        group: Optional[str],
        fn_name: str,
        held_at: "_HeldAt",
    ) -> None:
        if group is None:
            return
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return
        attr = node.attr
        # Locks themselves and group methods are not shared *state*.
        if f"{group}.{attr}" in self.locks:
            return
        if attr in self._group_methods.get(group, {}):
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        info.accesses.append(
            AttrAccess(
                attr=attr, group=group, write=write, path=ctx.path,
                line=node.lineno, col=node.col_offset, func=info.key,
                locks_held=held_at(node.lineno),
                in_init=fn_name == "__init__",
            )
        )

    # -- thread roots ----------------------------------------------------

    def _discover_thread_roots(self) -> None:
        for info in list(self.functions.values()):
            for call in info.calls:
                leaf = call.name.rsplit(".", 1)[-1]
                if leaf != "Thread":
                    continue
                target = self._thread_target(info, call)
                if target is not None and target in self.functions:
                    label = self.functions[target].name
                    self.thread_roots[target] = label

    def _thread_target(
        self, info: FunctionInfo, call: CallSite
    ) -> Optional[str]:
        """Resolve the ``target=`` of a Thread(...) call found at
        ``call``'s site by re-reading the AST is overkill — instead the
        call-site records of ``info`` already hold every callee name;
        the Thread target is recovered from the source line span."""
        ctx = self.files.get(call.path)
        if ctx is None:
            return None
        # Parse just the Thread(...) call's segment for its target kwarg.
        node = self._call_node_at(ctx, call)
        if node is None:
            return None
        target_expr: Optional[ast.AST] = None
        for kw in node.keywords:
            if kw.arg == "target":
                target_expr = kw.value
                break
        if target_expr is None and node.args:
            # Thread(group, target) positional form (rare).
            if len(node.args) >= 2:
                target_expr = node.args[1]
        if target_expr is None:
            return None
        name = dotted_name(target_expr)
        if name is None:
            # partial(self._loop, ...) / lambda: self._loop()
            if isinstance(target_expr, ast.Call) and target_expr.args:
                name = dotted_name(target_expr.args[0])
            elif isinstance(target_expr, ast.Lambda) and isinstance(
                target_expr.body, ast.Call
            ):
                name = dotted_name(target_expr.body.func)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and info.group:
            return self._group_methods.get(info.group, {}).get(parts[1])
        if len(parts) == 1:
            # nested def in the spawning function, or module function.
            nested_key = f"{info.key}.{parts[0]}"
            if nested_key in self.functions:
                return nested_key
            return self._module_funcs.get(info.path, {}).get(parts[0])
        return self._unique_methods.get(parts[-1]) or None

    @staticmethod
    def _call_node_at(ctx: FileContext, call: CallSite) -> Optional[ast.Call]:
        try:
            tree = ast.parse(ctx.source)
        except SyntaxError:
            return None
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and node.lineno == call.line
                and node.col_offset == call.col
                # A chained `Thread(...).start()` puts TWO Call nodes at
                # the same (line, col) — the outer `.start()` call first
                # in walk order. Matching the callee name picks the
                # Thread(...) call itself.
                and dotted_name(node.func) == call.name
            ):
                return node
        return None

    # -- derived queries -------------------------------------------------

    def roots_of(self, func_key: str) -> frozenset[str]:
        """The thread roots from which ``func_key`` is reachable
        through resolved call edges. Public functions (and anything
        they reach) additionally carry the synthetic ``caller`` root —
        request/HTTP threads enter there."""
        if self._roots_of is None:
            self._roots_of = self._compute_roots()
        return self._roots_of.get(func_key, frozenset())

    def _compute_roots(self) -> dict[str, frozenset[str]]:
        adj: dict[str, list[str]] = {}
        for key, info in self.functions.items():
            adj[key] = [
                c.callee for c in info.calls
                if c.callee is not None and c.callee in self.functions
            ]
        result: dict[str, set[str]] = {k: set() for k in self.functions}

        def bfs(starts: list[str], label: str) -> None:
            queue = list(starts)
            seen: set[str] = set(queue)
            while queue:
                cur = queue.pop()
                result[cur].add(label)
                for nxt in adj.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        queue.append(nxt)

        for root_key, label in self.thread_roots.items():
            bfs([root_key], label)
        public = [
            k for k, info in self.functions.items()
            if info.is_public and k not in self.thread_roots
        ]
        bfs(public, CALLER_ROOT)
        return {k: frozenset(v) for k, v in result.items()}

    def entry_locks(self, func_key: str) -> frozenset[str]:
        """Locks guaranteed held on *entry* to ``func_key``: the
        intersection, over every resolved call site, of the locks held
        at that site plus the caller's own entry locks. Public
        functions and thread roots can be entered from outside the
        index, so their entry set is empty. This is the guarded-by
        inference that makes ``# Callers hold self._lock`` helpers
        (brownout ``_step``, lifecycle ``_prune``) analyzable."""
        if self._entry_locks is None:
            self._entry_locks = self._compute_entry_locks()
        return self._entry_locks.get(func_key, frozenset())

    def _compute_entry_locks(self) -> dict[str, frozenset[str]]:
        callers: dict[str, list[tuple[str, frozenset[str]]]] = {}
        for key, info in self.functions.items():
            for call in info.calls:
                if call.callee and call.callee in self.functions:
                    callers.setdefault(call.callee, []).append(
                        (key, call.locks_held)
                    )
        universe = frozenset(self.locks)
        entry: dict[str, frozenset[str]] = {}
        for key, info in self.functions.items():
            callable_externally = (
                info.is_public
                or key in self.thread_roots
                or key not in callers
            )
            entry[key] = frozenset() if callable_externally else universe
        # Meet-over-call-sites to fixpoint (intersection only shrinks;
        # terminates). Functions stuck at `universe` sit on caller
        # cycles unreachable from any externally-callable function —
        # dead code; the value never matters.
        changed = True
        while changed:
            changed = False
            for key, sites in callers.items():
                if not entry[key]:
                    continue
                new: Optional[frozenset[str]] = None
                for caller_key, held in sites:
                    at_site = held | entry.get(caller_key, frozenset())
                    new = at_site if new is None else (new & at_site)
                if new is not None and new != entry[key]:
                    entry[key] = new
                    changed = True
        return entry

    def may_acquire(self, func_key: str) -> dict[str, tuple[str, ...]]:
        """Locks ``func_key`` may acquire, directly or transitively:
        lock key -> example call chain (function names, outermost
        first) ending at the acquiring function."""
        memo = self._may_acquire
        if func_key in memo:
            return memo[func_key]
        self._fixpoint(
            func_key, memo,
            direct=lambda info: {
                a.lock: (info.name,) for a in info.acquisitions
            },
        )
        return memo[func_key]

    def may_block(self, func_key: str) -> dict[str, tuple[str, ...]]:
        """Blocking primitives ``func_key`` may hit, directly or
        transitively: primitive name -> example call chain."""
        memo = self._may_block
        if func_key in memo:
            return memo[func_key]
        self._fixpoint(
            func_key, memo,
            direct=lambda info: {
                name: (info.name,) for name, _, _ in info.blocking
            },
        )
        return memo[func_key]

    def _fixpoint(
        self,
        start: str,
        memo: dict[str, dict[str, tuple[str, ...]]],
        direct: "_DirectFn",
    ) -> None:
        """Iterative DFS computing the transitive closure of ``direct``
        over the call graph, cycle-safe (locks/blocking discovered on a
        cycle propagate through the final stabilization sweep)."""
        order: list[str] = []
        seen: set[str] = set()
        stack = [start]
        while stack:
            cur = stack.pop()
            if cur in seen or cur in memo:
                continue
            seen.add(cur)
            order.append(cur)
            info = self.functions.get(cur)
            if info is None:
                continue
            for call in info.calls:
                if call.callee and call.callee in self.functions:
                    stack.append(call.callee)
        for cur in seen:
            info = self.functions.get(cur)
            memo[cur] = dict(direct(info)) if info is not None else {}
        # Propagate to fixpoint (small graphs; bounded by #locks).
        changed = True
        while changed:
            changed = False
            for cur in order:
                info = self.functions.get(cur)
                if info is None:
                    continue
                mine = memo[cur]
                for call in info.calls:
                    sub = memo.get(call.callee or "")
                    if not sub:
                        continue
                    for lock_key, chain in sub.items():
                        if lock_key not in mine:
                            mine[lock_key] = (info.name,) + chain
                            changed = True


# typing aliases used above (kept at module end: runtime-irrelevant)
from typing import Callable  # noqa: E402

_HeldAt = Callable[[int], "frozenset[str]"]
_DirectFn = Callable[[FunctionInfo], "dict[str, tuple[str, ...]]"]
