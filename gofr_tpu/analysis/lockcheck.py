"""Runtime lock-discipline validator (graftlint's dynamic half).

The static rules (GL020–GL022) model the serving thread mesh from the
AST; this module cross-checks that model against *real executions*. Set
``TPU_LOCKCHECK=1`` and every serving/service lock built through
:func:`make_lock` becomes an instrumented wrapper that records, per
thread, the stack of locks currently held, and checks two invariants at
each acquisition:

* **no order inversion** — acquiring ``B`` while holding ``A`` adds the
  edge ``A→B`` to a process-wide order graph; if a path ``B→…→A`` was
  ever observed (any thread, any time), the acquisition is recorded as
  a violation: under the wrong interleaving those two threads deadlock.
  Edges persist for the process lifetime, so the two halves of an
  inversion need not collide in time to be caught — one run of each
  path suffices.
* **no device sync while holding a lock** — the designated device-wait
  seams call :func:`note_device_sync`; reaching one with any
  instrumented lock held is recorded (a device wait under the submit
  lock convoys every submitting thread behind the device).

A blocking re-acquisition of a lock the same thread already holds would
*deadlock the test run*, so that case raises :class:`LockCheckError`
immediately instead of recording and hanging.

Violations are **recorded, not raised**, at the point of detection
(raising mid-hold would poison unrelated teardown): the chaos/CI suites
arm an autouse fixture that asserts :func:`violations` is empty after
each test. With ``TPU_LOCKCHECK`` unset (or ``0``), :func:`make_lock`
returns a plain ``threading.Lock`` — the instrumented path does not
exist, so the overhead is exactly zero by construction.

Cross-thread release is tolerated (``threading.Lock`` allows it, and
the profiler-capture slot is acquired by the scheduler thread and
released by the capture thread): release pops the lock from whichever
thread's stack holds it.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional, cast


def enabled() -> bool:
    """Is the validator armed (``TPU_LOCKCHECK`` truthy)?"""
    return os.environ.get("TPU_LOCKCHECK", "0").lower() not in (
        "", "0", "false", "no",
    )


class LockCheckError(RuntimeError):
    """Raised only for a blocking self-re-acquisition — the one
    violation that would hang the process if allowed to proceed."""


@dataclass(frozen=True)
class Violation:
    """One recorded invariant breach."""

    kind: str  # "order-inversion" | "device-sync-under-lock" | "self-deadlock"
    message: str
    thread: str
    held: tuple[str, ...]  # the thread's acquisition stack at detection


class _Registry:
    """Process-wide order graph + per-thread acquisition stacks."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # lock name -> names acquired at least once while it was held
        self._edges: dict[str, set[str]] = {}
        # (held, acquired) -> "thread/stack" witness of the first sight
        self._witness: dict[tuple[str, str], str] = {}
        # thread ident -> stack of held InstrumentedLock objects
        self._held: dict[int, list["InstrumentedLock"]] = {}
        self.violations: list[Violation] = []

    # -- helpers (call with self._mu held) -----------------------------

    def _stack(self, ident: Optional[int] = None) -> list["InstrumentedLock"]:
        key = threading.get_ident() if ident is None else ident
        return self._held.setdefault(key, [])

    def _path_exists(self, src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            cur = frontier.pop()
            if cur == dst:
                return True
            for nxt in self._edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    # -- events ---------------------------------------------------------

    def before_acquire(self, lock: "InstrumentedLock") -> None:
        """Blocking-acquire preflight: a self-re-acquisition would hang
        the run, so it raises instead of recording."""
        with self._mu:
            stack = self._stack()
            if lock in stack:
                names = tuple(x.name for x in stack)
                self.violations.append(
                    Violation(
                        kind="self-deadlock",
                        message=(
                            f"blocking re-acquisition of `{lock.name}` "
                            "by the thread already holding it"
                        ),
                        thread=threading.current_thread().name,
                        held=names,
                    )
                )
                raise LockCheckError(
                    f"lockcheck: `{lock.name}` re-acquired (blocking) by "
                    f"{threading.current_thread().name} while held "
                    f"(stack: {' -> '.join(names)}); this would deadlock"
                )

    def note_acquired(self, lock: "InstrumentedLock") -> None:
        with self._mu:
            stack = self._stack()
            thread = threading.current_thread().name
            for holder in stack:
                if holder.name == lock.name:
                    continue
                edge = (holder.name, lock.name)
                fresh = lock.name not in self._edges.setdefault(
                    holder.name, set()
                )
                if fresh:
                    self._edges[holder.name].add(lock.name)
                    self._witness[edge] = (
                        f"{thread}: "
                        + " -> ".join(x.name for x in stack)
                        + f" -> {lock.name}"
                    )
                # Inversion: a path back from the new lock to a holder
                # (excluding the edge just added — that trivial 2-cycle
                # is exactly what we look for, via the REVERSE edge).
                if self._path_exists(lock.name, holder.name):
                    reverse = self._witness.get(
                        (lock.name, holder.name),
                        "a transitive chain observed earlier",
                    )
                    self.violations.append(
                        Violation(
                            kind="order-inversion",
                            message=(
                                f"acquired `{lock.name}` while holding "
                                f"`{holder.name}`, but the opposite "
                                f"order was also observed ({reverse}); "
                                "these threads deadlock under the "
                                "wrong interleaving"
                            ),
                            thread=thread,
                            held=tuple(x.name for x in stack),
                        )
                    )
            stack.append(lock)

    def note_release(self, lock: "InstrumentedLock") -> None:
        with self._mu:
            stack = self._stack()
            if lock in stack:
                stack.remove(lock)
                return
            # Cross-thread release (the capture-slot idiom): pop it
            # from whichever thread still holds it.
            for other in self._held.values():
                if lock in other:
                    other.remove(lock)
                    return

    def clear(self) -> None:
        """Drop violations and the learned order graph IN PLACE.

        Every ``InstrumentedLock`` captures its registry reference at
        construction, so replacing the global registry object would
        orphan all previously minted locks (module-level locks, engine
        fixtures from earlier tests) — their events would land in a
        registry nobody reads.  Per-thread acquisition stacks are kept:
        locks held across the clear must still release-balance.
        """
        with self._mu:
            self._edges.clear()
            self._witness.clear()
            self.violations.clear()

    def note_device_sync(self, what: str) -> None:
        with self._mu:
            stack = self._stack()
            if not stack:
                return
            self.violations.append(
                Violation(
                    kind="device-sync-under-lock",
                    message=(
                        f"device sync `{what}` while holding "
                        + " -> ".join(x.name for x in stack)
                        + "; the device wait convoys every thread "
                        "contending for the lock(s)"
                    ),
                    thread=threading.current_thread().name,
                    held=tuple(x.name for x in stack),
                )
            )


class InstrumentedLock:
    """``threading.Lock``-shaped wrapper reporting to the registry.

    Only the mutex protocol the serving/service code uses is exposed:
    ``acquire``/``release``/``locked`` and the context manager."""

    __slots__ = ("name", "_reg", "_inner")

    def __init__(self, name: str, reg: _Registry) -> None:
        self.name = name
        self._reg = reg
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._reg.before_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._reg.note_acquired(self)
        return got

    def release(self) -> None:
        self._reg.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self.name} locked={self.locked()}>"


#: Built lazily on the first instrumented make_lock() call; stays None
#: forever when TPU_LOCKCHECK is off, which is what makes the disabled
#: path free: note_device_sync() is one global-is-None test.
_registry: Optional[_Registry] = None
_registry_mu = threading.Lock()


def make_lock(name: str) -> threading.Lock:
    """The serving/service lock constructor seam.

    Disabled (default): returns a plain ``threading.Lock`` — nothing
    instrumented is built, so there is no overhead to measure. Enabled:
    returns an :class:`InstrumentedLock` registered under ``name``
    (use ``"Class.attr"`` so runtime reports match the static model's
    lock keys)."""
    if not enabled():
        return threading.Lock()
    global _registry
    with _registry_mu:
        if _registry is None:
            _registry = _Registry()
    # The wrapper quacks like threading.Lock for every call site here;
    # the cast keeps annotated attributes (`_lock: threading.Lock`)
    # honest without weakening them to Any.
    return cast(threading.Lock, InstrumentedLock(name, _registry))


def note_device_sync(what: str) -> None:
    """Called at the designated device-wait seams (scheduler window
    fetch, lockstep barrier). Free when the validator is off."""
    reg = _registry
    if reg is not None:
        reg.note_device_sync(what)


def violations() -> list[Violation]:
    """Everything recorded so far (empty when disabled)."""
    reg = _registry
    return list(reg.violations) if reg is not None else []


def reset() -> None:
    """Drop recorded violations AND the learned order graph (test
    isolation: one test's lock order must not indict another's).

    Clears the live registry in place — existing ``InstrumentedLock``
    instances hold a reference to it, so swapping in a fresh registry
    would silently disconnect every lock minted before the reset."""
    reg = _registry
    if reg is not None:
        reg.clear()


def order_graph() -> dict:
    """The learned runtime lock-order graph, for ``/debug/lockgraph``.

    Returns ``{"enabled": bool, "edges": {held: [acquired, ...]},
    "witnesses": {"held -> acquired": "thread: stack"}}``. Edges are
    every ``A→B`` ordering the validator has OBSERVED this process —
    the dynamic counterpart of graftlint's static may-acquire model
    (GL021), so an operator can diff what the code could do against
    what this run actually did. Empty (enabled=False) when
    ``TPU_LOCKCHECK`` is off."""
    reg = _registry
    if reg is None:
        return {"enabled": False, "edges": {}, "witnesses": {}}
    with reg._mu:
        return {
            "enabled": True,
            "edges": {
                held: sorted(acquired)
                for held, acquired in sorted(reg._edges.items())
                if acquired
            },
            "witnesses": {
                f"{a} -> {b}": w
                for (a, b), w in sorted(reg._witness.items())
            },
        }


def assert_clean() -> None:
    """Raise AssertionError listing every recorded violation."""
    found = violations()
    if found:
        lines = "\n".join(
            f"- [{v.kind}] {v.thread}: {v.message}" for v in found
        )
        raise AssertionError(
            f"lockcheck: {len(found)} lock-discipline violation(s):\n"
            f"{lines}"
        )
