"""Test utilities (reference: ``pkg/gofr/testutil``).

The stdout/stderr capture harness (reference ``testutil/os.go:8-36``), a
configurable mock logger (``testutil/mock_logger.go``), and ``CustomError``
(``testutil/error.go``).
"""

from gofr_tpu.testutil.capture import stderr_output_for_func, stdout_output_for_func
from gofr_tpu.testutil.mock_logger import CapturedLog, MockLogger
from gofr_tpu.testutil.errors import CustomError

__all__ = [
    "stdout_output_for_func",
    "stderr_output_for_func",
    "MockLogger",
    "CapturedLog",
    "CustomError",
]
