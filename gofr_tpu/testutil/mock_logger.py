"""Capturing mock logger (reference ``testutil/mock_logger.go:19-37``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from gofr_tpu.logging.level import Level


@dataclass
class CapturedLog:
    level: Level
    message: Any


class MockLogger:
    """Records every call; assert via ``.logs`` / ``messages_at``."""

    def __init__(self, level: Level = Level.DEBUG) -> None:
        self.level = level
        self.logs: list[CapturedLog] = []

    def _record(self, level: Level, args, fmt=None) -> None:
        if level < self.level:
            return
        if fmt is not None:
            try:
                msg: Any = (fmt % args) if args else fmt
            except (TypeError, ValueError):
                msg = f"{fmt} {args!r}"
        elif len(args) == 1:
            msg = args[0]
        else:
            msg = " ".join(str(a) for a in args)
        self.logs.append(CapturedLog(level, msg))

    def messages_at(self, level: Level) -> list:
        return [log.message for log in self.logs if log.level == level]

    def change_level(self, level: Level) -> None:
        self.level = level

    # leveled methods
    def debug(self, *a): self._record(Level.DEBUG, a)
    def debugf(self, fmt, *a): self._record(Level.DEBUG, a, fmt)
    def log(self, *a): self._record(Level.INFO, a)
    def logf(self, fmt, *a): self._record(Level.INFO, a, fmt)
    def info(self, *a): self._record(Level.INFO, a)
    def infof(self, fmt, *a): self._record(Level.INFO, a, fmt)
    def notice(self, *a): self._record(Level.NOTICE, a)
    def noticef(self, fmt, *a): self._record(Level.NOTICE, a, fmt)
    def warn(self, *a): self._record(Level.WARN, a)
    def warnf(self, fmt, *a): self._record(Level.WARN, a, fmt)
    def error(self, *a): self._record(Level.ERROR, a)
    def errorf(self, fmt, *a): self._record(Level.ERROR, a, fmt)

    def fatal(self, *a):
        self._record(Level.FATAL, a)
        raise SystemExit(1)

    def fatalf(self, fmt, *a):
        self._record(Level.FATAL, a, fmt)
        raise SystemExit(1)
