"""stdout/stderr capture harness (reference ``testutil/os.go:8-36``):
run a function, return what it printed — used to assert on log output."""

from __future__ import annotations

import contextlib
import io
from typing import Callable


def stdout_output_for_func(fn: Callable[[], None]) -> str:
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        fn()
    return buf.getvalue()


def stderr_output_for_func(fn: Callable[[], None]) -> str:
    buf = io.StringIO()
    with contextlib.redirect_stderr(buf):
        fn()
    return buf.getvalue()
