"""CustomError (reference ``testutil/error.go:3-9``)."""


class CustomError(Exception):
    def __init__(self, message: str = "custom error") -> None:
        super().__init__(message)
