"""In-process MQTT 3.1.1 broker for tests.

The miniredis of the MQTT backend (SURVEY §4: the reference tests Redis
against a real in-process server rather than mocks): a real TCP listener
speaking enough MQTT 3.1.1 to exercise ``MQTTClient`` end to end —
CONNECT/CONNACK, SUBSCRIBE/SUBACK (with ``+``/``#`` wildcard filters),
UNSUBSCRIBE/UNSUBACK, PUBLISH routing at QoS 0/1 (PUBACK to the sender;
inbound PUBACKs from receivers accepted), PINGREQ/PINGRESP, DISCONNECT.
"""

from __future__ import annotations

import socket
import struct
import threading

from gofr_tpu.datasource.pubsub.mqtt import (
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBLISH,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    encode_str,
    read_packet,
    topic_matches,
    write_packet,
)


class _ClientConn:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.subs: dict[str, int] = {}  # filter → granted qos
        self.lock = threading.Lock()

    def send(self, ptype: int, payload: bytes, flags: int = 0) -> None:
        with self.lock:
            write_packet(self.sock, ptype, payload, flags)


class InProcMQTTBroker:
    """``with InProcMQTTBroker() as b: MQTTClient(port=b.port)``"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen()
        self.host, self.port = self._listener.getsockname()
        self._clients: set[_ClientConn] = set()
        self._lock = threading.Lock()
        self._closed = False
        self._next_pid = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="mqtt-broker-accept", daemon=True
        )
        self._accept_thread.start()

    # -- server loops -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = _ClientConn(sock)
            with self._lock:
                self._clients.add(conn)
            threading.Thread(
                target=self._serve, args=(conn,), name="mqtt-broker-conn",
                daemon=True,
            ).start()

    def _serve(self, conn: _ClientConn) -> None:
        try:
            while not self._closed:
                pkt = read_packet(conn.sock)
                if pkt is None or pkt.ptype == DISCONNECT:
                    return
                if pkt.ptype == CONNECT:
                    conn.send(CONNACK, bytes([0, 0]))
                elif pkt.ptype == SUBSCRIBE:
                    (pid,) = struct.unpack(">H", pkt.payload[:2])
                    rest, granted = pkt.payload[2:], bytearray()
                    while rest:
                        (flen,) = struct.unpack(">H", rest[:2])
                        filt = rest[2 : 2 + flen].decode("utf-8")
                        qos = rest[2 + flen]
                        conn.subs[filt] = min(qos, 1)
                        granted.append(min(qos, 1))
                        rest = rest[3 + flen :]
                    conn.send(SUBACK, struct.pack(">H", pid) + bytes(granted))
                elif pkt.ptype == UNSUBSCRIBE:
                    (pid,) = struct.unpack(">H", pkt.payload[:2])
                    rest = pkt.payload[2:]
                    while rest:
                        (flen,) = struct.unpack(">H", rest[:2])
                        conn.subs.pop(rest[2 : 2 + flen].decode("utf-8"), None)
                        rest = rest[2 + flen :]
                    conn.send(UNSUBACK, struct.pack(">H", pid))
                elif pkt.ptype == PUBLISH:
                    self._route(conn, pkt)
                elif pkt.ptype == PINGREQ:
                    conn.send(PINGRESP, b"")
                # inbound PUBACK (receiver acking qos1 delivery): accepted, no state
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                self._clients.discard(conn)
            try:
                conn.sock.close()
            except OSError:
                pass

    def _route(self, sender: _ClientConn, pkt) -> None:
        qos = (pkt.flags >> 1) & 0x03
        (tlen,) = struct.unpack(">H", pkt.payload[:2])
        topic = pkt.payload[2 : 2 + tlen].decode("utf-8")
        rest = pkt.payload[2 + tlen :]
        if qos:
            (pid,) = struct.unpack(">H", rest[:2])
            rest = rest[2:]
            sender.send(PUBACK, struct.pack(">H", pid))
        with self._lock:
            clients = list(self._clients)
        for client in clients:
            granted = max(
                (q for f, q in client.subs.items() if topic_matches(f, topic)),
                default=None,
            )
            if granted is None:
                continue
            out_qos = min(qos, granted)
            var = encode_str(topic)
            if out_qos:
                self._next_pid = self._next_pid % 65535 + 1
                var += struct.pack(">H", self._next_pid)
            try:
                client.send(PUBLISH, var + rest, flags=out_qos << 1)
            except OSError:
                pass

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        # shutdown() BEFORE close(): the accept thread is blocked inside
        # accept(), and closing the fd alone leaves the kernel socket
        # alive (still in LISTEN) until that syscall returns — which is
        # never without a new connection. shutdown wakes it, so the port
        # actually frees and a same-port restart can bind.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            try:
                c.sock.close()
            except OSError:
                pass

    def __enter__(self) -> "InProcMQTTBroker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
