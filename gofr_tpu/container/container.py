"""The central DI container (reference ``container/container.go:26-131``).

Owns the logger, metrics manager, and every configured datasource; creates
each from config at boot exactly like the reference's ``Create``
(``container/container.go:56-131``): Redis/SQL/PubSub gated on their env
keys, plus the net-new TPU backend gated on ``TPU_ENABLED``/``TPU_MODEL``
(SURVEY §2.6: the TPU client is a container member like ``SQL``/``Redis``).
Aggregate health mirrors ``container/health.go:8-28``.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from gofr_tpu.config.env import Config
from gofr_tpu.logging import Level, Logger, RemoteLevelLogger, level_from_string
from gofr_tpu.metrics import Manager, new_metrics_manager


class Container:
    def __init__(self, config: Config, logger: Optional[Logger] = None) -> None:
        self.config = config
        self.app_name = config.get_or_default("APP_NAME", "gofr-tpu-app")
        self.app_version = config.get_or_default("APP_VERSION", "dev")
        self.logger: Logger = logger or Logger(
            level=level_from_string(config.get("LOG_LEVEL"), Level.INFO)
        )
        self.metrics: Manager = new_metrics_manager(self.logger)

        self.sql = None
        self.redis = None
        self.pubsub = None
        self.mongo = None  # injected seam (reference datasource/mongo.go:8)
        self.tpu = None  # net-new: TPU inference backend (SURVEY §2.6)
        self.tpu_embed = None  # secondary encoder engine (TPU_EMBED_MODEL)
        self.services: dict[str, Any] = {}  # name → service.HTTP clients

        self._remote_logger: Optional[RemoteLevelLogger] = None

    # -- creation (reference container/container.go:41-131) --------------

    @classmethod
    def create(cls, config: Config, logger: Optional[Logger] = None) -> "Container":
        c = cls(config, logger=logger)
        c.logger.infof(
            "container created for app %s (version %s)", c.app_name, c.app_version
        )

        remote_url = config.get_or_default("REMOTE_LOG_URL", "")
        if remote_url:
            interval = float(config.get_or_default("REMOTE_LOG_FETCH_INTERVAL", "15"))
            c._remote_logger = RemoteLevelLogger(
                c.logger, remote_url, interval, metrics=c.metrics
            )
            c._remote_logger.start()

        c.register_framework_metrics()

        # Datasources are created lazily-by-config, each in its own module so
        # a missing backend never breaks boot (reference logs and continues).
        from gofr_tpu.datasource.redis import new_redis_from_config

        c.redis = new_redis_from_config(config, c.logger, c.metrics)

        from gofr_tpu.datasource.sql import new_sql_from_config

        c.sql = new_sql_from_config(config, c.logger, c.metrics)

        from gofr_tpu.datasource.pubsub import new_pubsub_from_config

        c.pubsub = new_pubsub_from_config(config, c.logger, c.metrics)

        from gofr_tpu.serving.backend import new_tpu_from_config

        c.tpu = new_tpu_from_config(config, c.logger, c.metrics)

        from gofr_tpu.serving.backend import new_tpu_embed_from_config

        c.tpu_embed = new_tpu_embed_from_config(config, c.logger, c.metrics)
        return c

    def use_mongo(self, client) -> None:
        """User-injected Mongo driver (reference ``gofr.go:376-378``)."""
        self.mongo = client

    def use_pubsub(self, client) -> None:
        """User-injected pub/sub client (same seam as ``use_mongo`` — lets
        apps wire a broker whose driver the framework doesn't bundle)."""
        self.pubsub = client

    # -- service registry (reference gofr.go:189-199) ---------------------

    def get_http_service(self, name: str):
        return self.services.get(name)

    def get_publisher(self):
        return self.pubsub

    def get_subscriber(self):
        return self.pubsub

    # -- framework metrics (reference container/container.go:143-172) -----

    def register_framework_metrics(self) -> None:
        m = self.metrics
        # System / app metrics.
        m.new_gauge("app_go_routines", "number of async tasks + threads")
        m.new_gauge("app_sys_memory_alloc", "resident memory bytes")
        m.new_gauge("app_sys_total_alloc", "total allocated bytes")
        m.new_gauge("app_go_numGC", "gc collection count")
        m.new_gauge("app_go_sys", "runtime sys bytes")
        # HTTP server/client (buckets follow container.go:153-154).
        http_buckets = (0.001, 0.003, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30)
        m.new_histogram("app_http_response", "HTTP server response time in s", http_buckets)
        m.new_histogram(
            "app_http_service_response", "outbound HTTP client response time in s", http_buckets
        )
        # Redis / SQL (container.go:158-163).
        m.new_histogram(
            "app_redis_stats", "redis command duration in ms",
            (0.05, 0.075, 0.1, 0.125, 0.15, 0.2, 0.3, 0.5, 0.75, 1, 2, 3),
        )
        m.new_histogram(
            "app_sql_stats", "sql query duration in ms",
            (0.05, 0.075, 0.1, 0.125, 0.15, 0.2, 0.3, 0.5, 0.75, 1, 2, 3, 4, 5, 7.5, 10),
        )
        m.new_gauge("app_sql_open_connections", "open sql connections")
        m.new_gauge("app_sql_inUse_connections", "in-use sql connections")
        # PubSub.
        m.new_counter("app_pubsub_publish_total_count", "messages published")
        m.new_counter("app_pubsub_publish_success_count", "publish successes")
        m.new_counter("app_pubsub_subscribe_total_count", "subscribe polls")
        m.new_counter("app_pubsub_subscribe_success_count", "messages handled")
        # Durable async serving plane (serving/async_serving.py;
        # TPU_ASYNC; docs/advanced-guide/resilience.md "Async serving &
        # delivery semantics"): the at-least-once delivery counters and
        # the two live-state gauges the lag control signal reads.
        m.new_counter(
            "app_tpu_async_consumed_total",
            "async request messages consumed (acked) by the serving plane",
        )
        m.new_counter(
            "app_tpu_async_published_total",
            "async reply messages published to the reply topic",
        )
        m.new_counter(
            "app_tpu_async_redelivered_total",
            "async request messages re-leased after a nack or an "
            "expired lease (at-least-once redelivery)",
        )
        m.new_counter(
            "app_tpu_async_dead_lettered_total",
            "async request messages parked on the dead-letter topic "
            "after exhausting their redelivery budget",
        )
        m.new_gauge(
            "app_tpu_async_lag",
            "request-topic backlog (ready messages) the async plane "
            "has not yet leased — the consumer-lag scale signal",
        )
        m.new_gauge(
            "app_tpu_async_inflight_leases",
            "async request messages leased and riding the engine",
        )
        # Net-new TPU serving metrics (SURVEY §2.6 per-chip observability).
        m.new_gauge("app_tpu_queue_depth", "dynamic batcher queue depth")
        m.new_gauge("app_tpu_hbm_used_bytes", "per-chip HBM in use")
        m.new_gauge("app_tpu_kv_slots_in_use", "KV-cache slots occupied")
        m.new_gauge("app_tpu_lora_adapters", "loaded LoRA adapters")
        m.new_histogram(
            "app_tpu_infer_latency", "device execute latency in s",
            (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2, 5),
        )
        m.new_histogram(
            "app_tpu_batch_size", "executed batch sizes",
            (1, 2, 4, 8, 16, 32, 64, 128, 256),
        )
        m.new_counter("app_tpu_tokens_generated", "tokens generated")
        m.new_counter(
            "app_tpu_prefix_hits", "prompts admitted via prefix-KV reuse"
        )
        m.new_histogram(
            "app_tpu_spec_tokens_per_step",
            "speculative decoding: tokens accepted per live step",
            (1, 1.5, 2, 2.5, 3, 4, 5, 6, 8),
        )
        m.new_gauge(
            "app_tpu_kv_blocks_free", "paged KV cache: free pool blocks"
        )
        # Automatic block-level prefix caching (TPU_AUTO_PREFIX;
        # docs/advanced-guide/prefix-caching.md): radix-index lookups at
        # admission, prompt tokens served by aliased cached blocks
        # instead of re-prefill, and the index's resident block count.
        m.new_counter(
            "app_tpu_prefix_lookup_total",
            "radix prefix-cache lookups at admission (result=hit|miss)",
        )
        m.new_counter(
            "app_tpu_prefix_hit_tokens_total",
            "prompt tokens admission-aliased from cached KV blocks "
            "(prefill skipped)",
        )
        m.new_gauge(
            "app_tpu_prefix_cached_blocks",
            "KV blocks currently held by the radix prefix index",
        )
        # Request-lifecycle resilience (docs/advanced-guide/resilience.md):
        # shedding, cancellation, deadlines, and the scheduler watchdog.
        m.new_counter(
            "app_tpu_requests_shed_total",
            "submits rejected by admission control (429/504 before a slot)",
        )
        m.new_counter(
            "app_tpu_requests_cancelled_total",
            "sequences retired mid-decode by cancel/disconnect",
        )
        m.new_counter(
            "app_tpu_deadline_exceeded_total",
            "sequences retired because their deadline expired",
        )
        m.new_counter(
            "app_tpu_watchdog_trips_total",
            "scheduler watchdog trips (stalled device step)",
        )
        # Self-healing supervision (serving/supervisor.py): warm engine
        # restarts, requests carried across them, and the health state
        # machine (0=SERVING 1=DEGRADED 2=RESTARTING 3=DOWN).
        m.new_counter(
            "app_tpu_engine_restarts_total",
            "supervisor warm restarts after a trip or scheduler crash",
        )
        m.new_counter(
            "app_tpu_requests_replayed_total",
            "in-flight requests replayed across an engine restart",
        )
        m.new_gauge(
            "app_tpu_engine_state",
            "engine health state machine "
            "(0=SERVING 1=DEGRADED 2=RESTARTING 3=DOWN)",
        )
        m.new_gauge(
            "app_http_service_circuit_open",
            "circuit breaker state per downstream service (1 = open)",
        )
        # Replica-tier failover (service/replica_pool.py): per-replica
        # routing state, mid-stream failovers, probe failures, hedges.
        m.new_gauge(
            "app_tpu_replica_state",
            "per-replica routing state "
            "(0=SERVING 1=DEGRADED 2=RESTARTING 3=DOWN/demoted)",
        )
        m.new_counter(
            "app_tpu_failovers_total",
            "in-flight requests adopted by a sibling replica after a "
            "replica died",
        )
        m.new_counter(
            "app_tpu_probe_failures_total",
            "synthetic health probes failed (replica demoted from routing)",
        )
        m.new_counter(
            "app_tpu_hedged_requests_total",
            "unary requests hedged or retried on a second replica",
        )
        # Multi-host data plane (service/replica_pool.py +
        # service/pool_scaler.py): pool composition by routing state,
        # load-adaptive scale events, and remote SSE streams resumed on
        # a sibling after a network loss.
        m.new_gauge(
            "app_tpu_pool_replicas",
            "replica-pool composition by routing state "
            "(serving/degraded/restarting/down/draining)",
        )
        m.new_counter(
            "app_tpu_scale_events_total",
            "pool-scaler resize events (direction=up|down)",
        )
        m.new_counter(
            "app_tpu_remote_stream_failovers_total",
            "remote SSE streams that died mid-stream and resumed on a "
            "sibling replica",
        )
        # Request-lifecycle observability (serving/observability.py;
        # docs/advanced-guide/observability.md): phase-latency
        # histograms — exactly one record per request per phase,
        # computed at retirement from host-side timestamps — and
        # per-window utilization gauges.
        lat_buckets = (
            0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
            1, 2.5, 5, 10, 30,
        )
        m.new_histogram(
            "app_tpu_queue_wait_seconds",
            "submit → admission into a KV slot", lat_buckets,
        )
        m.new_histogram(
            "app_tpu_prefill_seconds",
            "admission → prefill finalize (chunked)", lat_buckets,
        )
        m.new_histogram(
            "app_tpu_ttft_seconds",
            "submit → first token emitted", lat_buckets,
        )
        m.new_histogram(
            "app_tpu_inter_token_seconds",
            "per-request mean gap between generated tokens",
            (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1),
        )
        m.new_histogram(
            "app_tpu_e2e_seconds",
            "submit → retirement (whole request)",
            (0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120),
        )
        m.new_gauge(
            "app_tpu_batch_occupancy",
            "live decode slots / total slots, set once per window",
        )
        m.new_gauge(
            "app_tpu_decode_step_seconds",
            "decode-step duration (window dispatch→processed over its "
            "steps; includes pipeline queueing)",
        )
        m.new_gauge(
            "app_tpu_tokens_per_step",
            "client-visible tokens emitted per decode step, per window",
        )
        # Disaggregated prefill/decode tiers (TPU_REPLICA_ROLES;
        # docs/advanced-guide/resilience.md): cross-tier KV-block
        # transfers by outcome, their wall-clock cost, and whether the
        # pool is currently serving tiered or fused.
        m.new_counter(
            "app_tpu_tier_transfers_total",
            "prefill→decode KV-block transfers by outcome (result="
            "ok|fused|failed_over|local_fused|expired) and leg "
            "(leg=dma|device|wire|host|none)",
        )
        m.new_counter(
            "app_tpu_tier_transfer_bytes_total",
            "KV-cache bytes shipped by successful tier transfers, per "
            "leg (leg=dma|device|wire|host)",
        )
        m.new_counter(
            "app_tpu_tier_sources_total",
            "remote prefill-source pulls by outcome (kind="
            "hit|miss|rejected|error|expired) — the pull-mode twin of "
            "app_tpu_tier_transfers_total",
        )
        m.new_histogram(
            "app_tpu_tier_transfer_seconds",
            "prefill→decode transfer wall clock (extract→import)",
            (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1, 2.5, 5),
        )
        m.new_gauge(
            "app_tpu_tier_mode",
            "replica-pool serving mode (1 = disaggregated tiers, 0 = "
            "fused)",
        )
        # GSPMD-sharded serving (TPU_TP; docs/advanced-guide/
        # sharded-serving.md): devices per mesh axis (axis label; an
        # unsharded engine reports axis="tp" value 1).
        m.new_gauge(
            "app_tpu_mesh_devices",
            "serving mesh devices per axis (axis=tp|cp; 1 = unsharded)",
        )
        # Device-resource observability (serving/device_telemetry.py;
        # docs/advanced-guide/observability.md "Device-resource
        # signals"): the HBM ledger's per-component bytes and derived
        # headroom, XLA compile accounting with the steady-state
        # recompile counter (a compile after the warm-up fence is
        # always a fixed-shape-discipline bug), and paged-KV pool
        # saturation.
        m.new_gauge(
            "app_tpu_hbm_bytes",
            "HBM ledger bytes by component "
            "(params/lora/kv_pool/prefix_pool/workspace)",
        )
        m.new_gauge(
            "app_tpu_hbm_headroom_ratio",
            "free fraction of the per-device HBM budget "
            "(budget slack + free paged-KV blocks)",
        )
        m.new_counter(
            "app_tpu_compiles_total",
            "XLA program compiles by serving program",
        )
        m.new_histogram(
            "app_tpu_compile_seconds",
            "wall clock of a compiling call (trace + XLA compile — the "
            "latency a request actually pays)",
            (0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120),
        )
        m.new_counter(
            "app_tpu_steady_state_recompiles_total",
            "compiles AFTER the warm-up fence — always a fixed-shape-"
            "discipline bug (graftlint GL015 is the static twin)",
        )
        m.new_gauge(
            "app_tpu_kv_pool_occupancy_ratio",
            "paged KV pool: used blocks / total blocks",
        )
        m.new_gauge(
            "app_tpu_kv_pool_fragmentation_ratio",
            "paged KV pool: radix-cached (reclaimable-under-pressure) "
            "blocks / used blocks",
        )
        # Tenant attribution + SLO burn rates (serving/tenant_ledger.py
        # + serving/slo.py; docs/advanced-guide/observability.md "Tenant
        # attribution & SLOs"). Tenant labels are CLAMPED to the first
        # TPU_TENANT_LABEL_MAX distinct tenants (overflow folds into
        # tenant="_other"; the full table is /debug/tenants) — tenant
        # ids are request-controlled strings and must never become
        # unbounded label cardinality (graftlint GL016 is the static
        # twin of the clamp).
        m.new_counter(
            "app_tpu_tenant_tokens_total",
            "tokens attributed per tenant (phase=prefill|decode; "
            "label-clamped, overflow in tenant=_other)",
        )
        m.new_counter(
            "app_tpu_tenant_kv_block_seconds_total",
            "paged-KV occupancy attributed per tenant "
            "(block·seconds; Σ tenants == pool-wide occupancy integral)",
        )
        m.new_counter(
            "app_tpu_tenant_requests_total",
            "requests per tenant by outcome "
            "(ok|shed|cancelled|deadline|error)",
        )
        m.new_gauge(
            "app_tpu_slo_burn_rate",
            "error-budget burn rate per objective and window "
            "(slo=ttft|e2e|availability, window=5m|1h; 1.0 = spending "
            "exactly the budget)",
        )
        m.new_gauge(
            "app_tpu_slo_compliant",
            "1 while every SLO burn rate is within budget, else 0",
        )
        m.new_gauge(
            "app_tpu_slo_tenant_burn_rate",
            "per-tenant-override burn rate (TPU_SLO_TENANT_* knobs; "
            "label set bounded by configuration, not by traffic)",
        )
        # Brownout overload control (serving/brownout.py; docs/
        # advanced-guide/resilience.md "Brownout & overload control"):
        # the degradation-ladder level, its transitions, and the
        # per-action counters (clamp_tokens / suppress_hedge /
        # skip_probe / shed_<class> — all bounded vocabularies).
        m.new_gauge(
            "app_tpu_brownout_level",
            "brownout degradation level (0 = nominal .. 3 = replica "
            "deprioritized from routing)",
        )
        m.new_counter(
            "app_tpu_brownout_transitions_total",
            "brownout ladder transitions (direction=up|down)",
        )
        m.new_counter(
            "app_tpu_brownout_actions_total",
            "brownout actions taken (action=clamp_tokens|"
            "suppress_hedge|skip_probe|shed_<slo class>)",
        )
        # Scheduler-loop profiler (serving/loop_profiler.py; docs/
        # advanced-guide/observability.md "Scheduler-loop signals"):
        # per-phase wall time of the last scheduler pass (the bounded
        # phase vocabulary sums to pass wall time), the busy fraction
        # over a rolling pass window, the host-bookkeeping share of
        # busy time (THE "is host bookkeeping starving the TPU"
        # signal), and the hysteretic stall-anomaly counter.
        m.new_gauge(
            "app_tpu_loop_phase_seconds",
            "scheduler-loop pass wall seconds by phase (phase=reap|"
            "ledger|brownout|control|sweep|tier_import|prefill|"
            "emit_flush|dispatch|device_window|idle|other; sums to "
            "pass wall time)",
        )
        m.new_gauge(
            "app_tpu_loop_utilization",
            "busy fraction of scheduler-loop wall time over the "
            "rolling pass window (1 - idle share)",
        )
        m.new_gauge(
            "app_tpu_loop_host_overhead_ratio",
            "host-bookkeeping share of busy scheduler-loop time "
            "(busy minus the device-window seam, over busy)",
        )
        m.new_counter(
            "app_tpu_loop_stalls_total",
            "scheduler-loop stall anomalies (pass over TPU_LOOP_STALL_S "
            "or TPU_LOOP_STALL_FACTOR x rolling p95; kind=absolute|p95)",
        )
        # Control plane (serving/control_plane.py; docs/advanced-guide/
        # resilience.md "Control plane"): per-signal guard health, the
        # per-tenant brownout ladder (label set bounded by the ladder
        # table cap, not by traffic), advertised scale pressure, and
        # the per-loop action counters — all bounded vocabularies.
        m.new_gauge(
            "app_tpu_control_signal_health",
            "control-plane signal guard health (signal=<registered "
            "name>; 1.0 = fresh+finite, 0.5 = riding last-good value, "
            "0.0 = observe-only: the loop it feeds holds state)",
        )
        m.new_gauge(
            "app_tpu_control_tenant_level",
            "per-tenant brownout ladder level (0 = nominal .. 3 = "
            "full shed for that tenant; bounded by "
            "TPU_CONTROL_TENANT_TABLE)",
        )
        m.new_gauge(
            "app_tpu_control_scale_pressure",
            "control-plane scale pressure advertised to the pool "
            "scaler (source=host|predictive; 1 while the loop holds "
            "sustained pressure)",
        )
        m.new_counter(
            "app_tpu_control_actions_total",
            "control-plane actions (loop=tenant_brownout|"
            "host_pressure|predictive, action=enter|exit|clamp_tokens|"
            "thin_admit|shed|scale_pressure)",
        )

    def push_system_metrics(self) -> None:
        """Per-scrape system gauges (reference ``metrics/handler.go:21-35``)."""
        import gc
        import threading

        self.metrics.set_gauge("app_go_routines", threading.active_count())
        try:
            with open("/proc/self/statm") as fp:
                rss = int(fp.read().split()[1]) * 4096
        except Exception:
            rss = 0
        self.metrics.set_gauge("app_sys_memory_alloc", rss)
        self.metrics.set_gauge("app_go_numGC", sum(s.get("collections", 0) for s in gc.get_stats()))

    # -- health (reference container/health.go:8-28) ----------------------

    def health(self) -> dict:
        out: dict[str, Any] = {
            "name": self.app_name,
            "version": self.app_version,
            "status": "UP",
            "startedAt": getattr(self, "_started_at", ""),
        }
        details: dict[str, Any] = {}
        for name in ("sql", "redis", "pubsub", "tpu", "tpu_embed", "mongo"):
            ds = getattr(self, name)
            if ds is None or not hasattr(ds, "health_check"):
                # health_check is opt-in for injected clients (use_mongo /
                # use_pubsub) — a minimal client must not flip the app to
                # DEGRADED just for lacking one.
                continue
            try:
                check = ds.health_check()
            except Exception as exc:
                check = {"status": "DOWN", "error": str(exc)}
            details[name] = check
            if check.get("status") != "UP":
                out["status"] = "DEGRADED"
        for svc_name, svc in self.services.items():
            try:
                check = svc.health_check()
            except Exception as exc:
                check = {"status": "DOWN", "error": str(exc)}
            details[f"service:{svc_name}"] = check
            if check.get("status") != "UP":
                out["status"] = "DEGRADED"
        out["details"] = details
        return out

    def mark_started(self) -> None:
        self._started_at = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    async def close(self) -> None:
        for name in ("sql", "redis", "pubsub", "tpu", "tpu_embed", "mongo"):
            ds = getattr(self, name)
            if ds is not None and hasattr(ds, "close"):
                try:
                    res = ds.close()
                    if hasattr(res, "__await__"):
                        await res
                except Exception:
                    pass
        if self._remote_logger is not None:
            self._remote_logger.stop()
