"""Dependency-injection container (reference: ``pkg/gofr/container``)."""

from gofr_tpu.container.container import Container

__all__ = ["Container"]
