"""The App (reference ``pkg/gofr/gofr.go:35-170``).

Owns config, container, router, middleware, and all servers. Lifecycle:

* ``App()`` — load ``configs/`` dotenv, create the container (datasources by
  config), initialise tracing (reference ``New()``, ``gofr.go:62-96``);
* route verbs ``get/post/put/patch/delete`` usable directly or as
  decorators (reference ``gofr.go:202-219``);
* ``run()`` — start metrics server (:2121), HTTP server (:8000), gRPC server
  (:9000, only when a service is registered), and subscriber loops, then
  block until SIGINT/SIGTERM and shut down gracefully — the drain the
  reference lacks (``gofr.go:169`` blocks forever; SURVEY §3.1).

Default ports mirror the reference's ``default.go:3-7``.
"""

from __future__ import annotations

import asyncio
import signal
from typing import Callable, Optional

from gofr_tpu.config.env import new_env_file
from gofr_tpu.container import Container
from gofr_tpu.handler import alive_handler, favicon_handler, health_handler, wrap_handler
from gofr_tpu.http.middleware import (
    apikey_auth_middleware,
    basic_auth_middleware,
    cors_middleware,
    logging_middleware,
    metrics_middleware,
    oauth_middleware,
    tracer_middleware,
)
from gofr_tpu.http.router import Router
from gofr_tpu.http.server import HTTPServer
from gofr_tpu.logging import Logger, level_from_string
from gofr_tpu.tracing import Tracer, exporter_from_config, set_tracer

DEFAULT_HTTP_PORT = 8000
DEFAULT_GRPC_PORT = 9000
DEFAULT_METRICS_PORT = 2121


class App:
    def __init__(self, config_dir: str = "./configs", config=None) -> None:
        bootstrap_logger = Logger()
        self.config = config if config is not None else new_env_file(config_dir, bootstrap_logger)
        self.container = Container.create(self.config)
        self.logger = self.container.logger
        self.logger.change_level(
            level_from_string(self.config.get("LOG_LEVEL"), self.logger.level)
        )

        tracer = Tracer(
            service_name=self.container.app_name,
            exporter=exporter_from_config(self.config, self.logger),
        )
        set_tracer(tracer)
        self._tracer = tracer

        self.router = Router(logger=self.logger)
        # Default chain, reference http/router.go:23-28.
        self.router.use_middleware(
            tracer_middleware(tracer),
            logging_middleware(self.logger),
            cors_middleware(),
            metrics_middleware(self.container.metrics),
        )

        self.http_port = int(self.config.get_or_default("HTTP_PORT", str(DEFAULT_HTTP_PORT)))
        self.metrics_port = int(
            self.config.get_or_default("METRICS_PORT", str(DEFAULT_METRICS_PORT))
        )
        self.grpc_port = int(self.config.get_or_default("GRPC_PORT", str(DEFAULT_GRPC_PORT)))

        from gofr_tpu.subscriber import SubscriptionManager

        self._subscriptions = SubscriptionManager(self.container)
        # The durable async serving plane (serving/async_serving.py;
        # TPU_ASYNC=1). Built in start() AFTER the engine so its
        # consumer loop never races engine warm-up; None when off.
        self._async_plane = None
        self._grpc_services: list = []
        self._grpc_server = None
        self._http_server: Optional[HTTPServer] = None
        self._metrics_server: Optional[HTTPServer] = None
        self._stop_event: Optional[asyncio.Event] = None

    # -- routing (reference gofr.go:202-227) -------------------------------

    def add_route(self, method: str, path: str, handler: Callable) -> None:
        self.router.add(method, path, wrap_handler(handler, self.container))

    def _verb(self, method: str, path: str, handler: Optional[Callable]):
        if handler is not None:
            self.add_route(method, path, handler)
            return handler

        def decorator(fn: Callable):
            self.add_route(method, path, fn)
            return fn

        return decorator

    def get(self, path: str, handler: Optional[Callable] = None):
        return self._verb("GET", path, handler)

    def post(self, path: str, handler: Optional[Callable] = None):
        return self._verb("POST", path, handler)

    def put(self, path: str, handler: Optional[Callable] = None):
        return self._verb("PUT", path, handler)

    def patch(self, path: str, handler: Optional[Callable] = None):
        return self._verb("PATCH", path, handler)

    def delete(self, path: str, handler: Optional[Callable] = None):
        return self._verb("DELETE", path, handler)

    def use_middleware(self, *mws) -> None:
        """Custom middleware (reference ``gofr.go:372``)."""
        self.router.use_middleware(*mws)

    def use_mongo(self, client) -> None:
        """Inject a Mongo driver (reference ``gofr.go:376-378``)."""
        self.container.use_mongo(client)

    def use_pubsub(self, client) -> None:
        """Inject a pub/sub client for brokers without bundled drivers."""
        self.container.use_pubsub(client)

    # -- auth enablers (reference gofr.go:310-344) -------------------------

    def enable_basic_auth(self, users: dict[str, str]) -> None:
        self.router.use_middleware(basic_auth_middleware(users=users))

    def enable_basic_auth_with_validator(self, validate_func) -> None:
        self.router.use_middleware(
            basic_auth_middleware(validate_func=validate_func, container=self.container)
        )

    def enable_api_key_auth(self, *keys: str) -> None:
        self.router.use_middleware(apikey_auth_middleware(keys=keys))

    def enable_api_key_auth_with_validator(self, validate_func) -> None:
        self.router.use_middleware(
            apikey_auth_middleware(validate_func=validate_func, container=self.container)
        )

    def enable_oauth(self, jwks_url: str, refresh_interval_s: float = 300.0) -> None:
        from gofr_tpu.http.middleware import JWKSProvider

        provider = JWKSProvider(jwks_url, refresh_interval_s, logger=self.logger)
        provider.start()
        self.router.use_middleware(oauth_middleware(jwks=provider))

    # -- pubsub / services / migrations ------------------------------------

    def subscribe(self, topic: str, handler: Optional[Callable] = None):
        """Register a subscription handler (reference ``gofr.go:346-354``)."""
        if handler is not None:
            self._subscriptions.register(topic, handler)
            return handler

        def decorator(fn: Callable):
            self._subscriptions.register(topic, fn)
            return fn

        return decorator

    def add_http_service(self, name: str, address: str, *options) -> None:
        """Register a downstream service client (reference ``gofr.go:189-199``)."""
        from gofr_tpu.service import new_http_service

        if name in self.container.services:
            self.logger.warnf("service %s already registered; overwriting", name)
        self.container.services[name] = new_http_service(
            address,
            self.logger,
            self.container.metrics,
            *options,
        )

    def migrate(self, migrations: dict) -> None:
        """Run versioned migrations (reference ``gofr.go:243-248``)."""
        from gofr_tpu.migration import run as run_migrations

        try:
            run_migrations(migrations, self.container)
        except Exception:
            import traceback

            self.logger.errorf("migration panicked:\n%s", traceback.format_exc())

    def add_rest_handlers(self, entity) -> None:
        """Auto-register CRUD routes for a dataclass entity
        (reference ``gofr.go:356-369``)."""
        from gofr_tpu.crud import register_crud_handlers

        register_crud_handlers(self, entity)

    def register_service(self, add_servicer_fn, servicer) -> None:
        """Register a gRPC service (reference ``gofr.go:55-59``). The server
        starts only if at least one service is registered
        (``gofr.go:150-157``)."""
        self._grpc_services.append((add_servicer_fn, servicer))

    # -- lifecycle ----------------------------------------------------------

    def _install_wellknown(self) -> None:
        self.add_route("GET", "/.well-known/health", health_handler(self.container))
        self.add_route("GET", "/.well-known/alive", alive_handler)
        self.add_route("GET", "/favicon.ico", favicon_handler)

    async def start(self) -> None:
        """Bind all servers (ephemeral-port friendly); used by run() and tests."""
        self._install_wellknown()
        self.container.mark_started()

        self._metrics_server = HTTPServer(
            self._metrics_handler(), port=self.metrics_port, logger=self.logger
        )
        await self._metrics_server.start()
        self.metrics_port = self._metrics_server.port
        self.logger.infof("metrics server started on :%d/metrics", self.metrics_port)

        self._http_server = HTTPServer(self.router, port=self.http_port, logger=self.logger)
        await self._http_server.start()
        self.http_port = self._http_server.port

        if self._grpc_services:
            from gofr_tpu.grpc.server import GRPCServer

            self._grpc_server = GRPCServer(
                self.grpc_port, self.logger, self.container
            )
            for add_fn, servicer in self._grpc_services:
                self._grpc_server.register(add_fn, servicer)
            await self._grpc_server.start()
            self.grpc_port = self._grpc_server.port

        for engine in (self.container.tpu, self.container.tpu_embed):
            if engine is not None and hasattr(engine, "start"):
                await engine.start()

        if self.container.tpu is not None:
            from gofr_tpu.serving.async_serving import (
                new_async_plane_from_config,
            )

            self._async_plane = new_async_plane_from_config(
                self.config, self.container.tpu,
                metrics=self.container.metrics, logger=self.logger,
            )
            if self._async_plane is not None:
                self._async_plane.start()
                self.logger.infof(
                    "async serving plane consuming %r -> %r (dlq %r)",
                    self._async_plane.request_topic,
                    self._async_plane.reply_topic,
                    self._async_plane.dlq_topic,
                )

        self._subscriptions.start()

    async def stop(self) -> None:
        await self._subscriptions.stop()
        # TPU_DRAIN_S > 0: graceful engine drain — in-flight generations
        # complete (up to the deadline) while new submissions get 503,
        # so a rolling restart doesn't fail live requests.
        drain_s = float(self.config.get_or_default("TPU_DRAIN_S", "0"))
        if self._async_plane is not None:
            # Drain BEFORE the engine stops: finished async work still
            # publishes its replies, and unfinished leases are nacked
            # back to the broker (budget refunded) instead of dropped.
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._async_plane.stop(drain_s)
            )
            self._async_plane.broker.close()
        for engine in (self.container.tpu, self.container.tpu_embed):
            if engine is not None and hasattr(engine, "stop"):
                import inspect

                params = inspect.signature(engine.stop).parameters
                if "drain_s" in params:
                    await engine.stop(drain_s=drain_s)
                else:  # injected engines without the kwarg
                    await engine.stop()
        if self._grpc_server is not None:
            await self._grpc_server.stop()
        for server in (self._http_server, self._metrics_server):
            if server is not None:
                await server.shutdown()
        await self.container.close()
        self._tracer.shutdown()

    async def _run_async(self) -> None:
        await self.start()
        self._stop_event = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._stop_event.set)
            except NotImplementedError:
                pass
        await self._stop_event.wait()
        self.logger.info("shutting down gracefully")
        await self.stop()

    def run(self) -> None:
        """Blocking entrypoint (reference ``gofr.go:114-170``)."""
        try:
            asyncio.run(self._run_async())
        except KeyboardInterrupt:
            pass

    # -- metrics endpoint ---------------------------------------------------

    def _static_lockgraph(self) -> dict:
        """GL021's static may-acquire-while-holding model over this
        installed package, built once per process and cached (it
        re-parses every module): ``{"edges": {(held, acquired):
        (path, line)}, "witnesses": {...}}``. Empty on any failure —
        /debug/lockgraph degrades to runtime-only, never 500s."""
        cached = getattr(self, "_static_lockgraph_cache", None)
        if cached is not None:
            return cached
        graph: dict = {"edges": {}, "witnesses": {}}
        try:
            import os as _os

            import gofr_tpu as _pkg
            from gofr_tpu.analysis.core import build_index
            from gofr_tpu.analysis.rules import may_acquire_while_holding

            pkg_dir = _os.path.dirname(_os.path.abspath(_pkg.__file__))
            index = build_index([pkg_dir], root=_os.path.dirname(pkg_dir))
            if index is not None:
                witness = may_acquire_while_holding(index)
                graph = {
                    "edges": {
                        pair: (path, line)
                        for pair, (path, line, _) in witness.items()
                    },
                    "witnesses": {
                        f"{a} -> {b}": (
                            f"{path}:{line} via {' -> '.join(chain)}"
                        )
                        for (a, b), (path, line, chain)
                        in sorted(witness.items())
                    },
                }
        except Exception:  # noqa: BLE001 — debug surface, never 500
            pass
        self._static_lockgraph_cache = graph
        return graph

    def _metrics_handler(self):
        from gofr_tpu.http.proto import Response
        from gofr_tpu.metrics import render_prometheus

        container = self.container

        def engine_report(method: str) -> Response:
            """One JSON ops read per engine (`tpu`, `tpu_embed`) — or
            per replica when `container.tpu` is a ReplicaPool — from an
            engine-shaped `method()` report. The shared shape of
            /debug/flight, /debug/capacity, /debug/tenants,
            /debug/slo, /debug/brownout, and /debug/loop."""
            import json as _json

            reports: dict = {}
            for name, eng in (
                ("tpu", container.tpu), ("tpu_embed", container.tpu_embed)
            ):
                if eng is None:
                    continue
                fn = getattr(eng, method, None)
                if not callable(fn):
                    continue
                try:
                    reports[name] = fn()
                except Exception as exc:  # noqa: BLE001 — debug surface
                    reports[name] = {"error": str(exc)}
            return Response(
                status=200,
                headers={"Content-Type": "application/json"},
                body=_json.dumps(reports).encode(),
            )

        async def handler(raw) -> Response:
            path = raw.target.split("?")[0]
            if path == "/metrics":
                container.push_system_metrics()
                body = render_prometheus(container.metrics, app_name=container.app_name)
                return Response(
                    status=200,
                    headers={"Content-Type": "text/plain; version=0.0.4"},
                    body=body.encode(),
                )
            if path == "/.well-known/alive":
                return Response(
                    status=200,
                    headers={"Content-Type": "application/json"},
                    body=b'{"status":"UP"}',
                )
            # /debug/* — ops surface on the metrics port (net-new: the
            # closest Go analog is pprof-on-metrics-port, which the
            # reference does not ship; TPU serving makes the equivalents
            # indispensable: a wedged device relay shows up as a thread
            # parked in a jit dispatch, and device traces answer "where
            # does the step go" without a redeploy).
            if path == "/debug/threads":
                import sys as _sys
                import threading as _threading
                import traceback as _traceback

                names = {
                    t.ident: t.name for t in _threading.enumerate()
                }
                lines = []
                for ident, frame in _sys._current_frames().items():
                    lines.append(
                        f"Thread {names.get(ident, '?')} (ident {ident}):"
                    )
                    lines.extend(
                        ln.rstrip()
                        for ln in _traceback.format_stack(frame)
                    )
                    lines.append("")
                return Response(
                    status=200,
                    headers={"Content-Type": "text/plain"},
                    body="\n".join(lines).encode(),
                )
            if path == "/debug/engine":
                import json as _json

                stats = {}
                for name, eng in (
                    ("tpu", container.tpu), ("tpu_embed", container.tpu_embed)
                ):
                    if eng is None:
                        continue
                    try:
                        stats[name] = eng.health_check()
                    except Exception as exc:  # noqa: BLE001 — debug surface
                        stats[name] = {"error": str(exc)}
                return Response(
                    status=200,
                    headers={"Content-Type": "application/json"},
                    body=_json.dumps(stats).encode(),
                )
            if path == "/debug/flight":
                # The serving flight recorder (docs/advanced-guide/
                # observability.md): per-request lifecycle timelines —
                # phase durations, token counts, prefix-cache hits,
                # shed/cancel/replay/failover annotations, trace ids —
                # from a fixed-size ring with slow/errored requests
                # pinned so a burst can't evict the interesting ones.
                return engine_report("flight_records")
            if path == "/debug/capacity":
                # Device-resource capacity (docs/advanced-guide/
                # observability.md "Device-resource signals"): the HBM
                # ledger (per-component bytes, budget, headroom), XLA
                # compile counts with the steady-state recompile
                # counter, and paged-KV pool pressure — the operator's
                # one read for "is this pod running out of the
                # resources that actually bound it".
                return engine_report("capacity_report")
            if path == "/debug/tenants":
                # Tenant attribution (docs/advanced-guide/
                # observability.md "Tenant attribution and SLOs"): the
                # FULL unclamped per-tenant table — tokens by phase,
                # KV-block·seconds, outcome counts, live queue share —
                # next to the clamped Prometheus export. The operator's
                # one read for "which tenant is eating the pod".
                return engine_report("tenant_report")
            if path == "/debug/slo":
                # SLO burn-rate state (docs/advanced-guide/
                # observability.md): per-objective multi-window burn
                # rates and the compliance bit — the "is the service
                # breaking its promise right now" read.
                return engine_report("slo_report")
            if path == "/debug/brownout":
                # Brownout-ladder state (docs/advanced-guide/
                # resilience.md "Brownout & overload control"): the
                # degradation level, AIMD budget factor, thresholds,
                # per-action counters — what the burn-rate actuator is
                # DOING about the /debug/slo signal right now.
                return engine_report("brownout_report")
            if path == "/debug/loop":
                # Scheduler-loop profiler (docs/advanced-guide/
                # observability.md "Scheduler-loop signals"): per-phase
                # pass-time attribution, loop utilization, the
                # host-overhead ratio ("is host bookkeeping starving
                # the TPU"), and the pinned stall-anomaly records —
                # where a scheduler pass's wall time goes, without an
                # operator having to know when to run /debug/tpu-trace.
                return engine_report("loop_report")
            if path == "/debug/control":
                # Control-plane state (docs/advanced-guide/
                # resilience.md "Control plane"): per-signal guard
                # status (ok / last_good / observe_only), each loop's
                # state — the per-tenant brownout table, host-pressure
                # and predictive hold-down timers — and the last
                # decisions ring. The operator's one read for "which
                # loop acted, on what evidence, and which sensors is
                # it no longer trusting".
                return engine_report("control_report")
            if path == "/debug/async":
                # Async serving plane state (docs/advanced-guide/
                # resilience.md "Async serving & delivery semantics"):
                # topics + delivery knobs, consumer lag, in-flight
                # leases, the delivery counters (consumed / published /
                # redelivered / dead-lettered), and the dedup ledger's
                # occupancy — the operator's one read for "is async
                # traffic flowing, backing up, or dead-lettering".
                import json as _json

                plane = self._async_plane
                body_async = (
                    {"enabled": False} if plane is None
                    else plane.report()
                )
                return Response(
                    status=200,
                    headers={"Content-Type": "application/json"},
                    body=_json.dumps(body_async).encode(),
                )
            if path == "/debug/lockgraph":
                # Lock-order graphs (docs/advanced-guide/
                # resilience.md): the RUNTIME order graph TPU_LOCKCHECK
                # learned this process, the STATIC may-acquire-while-
                # holding model graftlint's GL021 derives from the AST,
                # and their diff — a runtime edge the static model
                # lacks means the model under-approximates (or a lock
                # bypassed make_lock); a static edge never observed is
                # untested ordering, not a bug. The static half is
                # built once and cached (it parses the package).
                import json as _json

                from gofr_tpu.analysis import lockcheck as _lockcheck

                runtime = _lockcheck.order_graph()
                static = self._static_lockgraph()
                run_edges = {
                    (a, b)
                    for a, bs in runtime["edges"].items() for b in bs
                }
                static_edges = set(static["edges"])
                body = {
                    "runtime": runtime,
                    "static": {
                        "edges": sorted(
                            f"{a} -> {b}" for a, b in static_edges
                        ),
                        "witnesses": static["witnesses"],
                    },
                    "diff": {
                        "runtime_only": sorted(
                            f"{a} -> {b}"
                            for a, b in run_edges - static_edges
                        ),
                        "static_only": sorted(
                            f"{a} -> {b}"
                            for a, b in static_edges - run_edges
                        ),
                    },
                    "violations": [
                        {
                            "kind": v.kind,
                            "thread": v.thread,
                            "message": v.message,
                        }
                        for v in _lockcheck.violations()
                    ],
                }
                return Response(
                    status=200,
                    headers={"Content-Type": "application/json"},
                    body=_json.dumps(body).encode(),
                )
            if path == "/ops/tier-import":
                # Wire-leg tier transfers (docs/advanced-guide/
                # resilience.md "Disaggregated prefill/decode"): a
                # remote prefill pod POSTs a finished prompt's KV
                # blocks here (length-prefixed binary payload) so the
                # separately-submitted request admission-aliases them
                # zero-copy. Validation mirrors the in-proc handoff
                # (geometry fingerprint + re-computed CRC); every
                # rejection is a 2xx/4xx "the request will re-prefill"
                # — never a 5xx, never a wrong answer. Lives on the
                # ops port: block payloads are operator-tier traffic,
                # not dataplane requests.
                import json as _json

                if raw.method != "POST":
                    return Response(
                        status=405,
                        headers={"Allow": "POST"},
                        body=b'{"error": "POST a KVB1 payload"}',
                    )
                from gofr_tpu.ops.kv_cache import (
                    HANDLE_MAGIC,
                    handle_from_wire,
                    payload_from_wire,
                )

                body_bytes = raw.body or b""
                try:
                    if body_bytes[:4] == HANDLE_MAGIC:
                        # The dma leg: the exporter POSTs a claim
                        # TICKET; this side pulls the bytes directly
                        # from the exporter's transfer server. Every
                        # redemption failure is a 200 "stale" — the
                        # exporter's ladder bans the dma rung and
                        # reships the same blocks inline via wire.
                        from gofr_tpu.service.dma import (
                            DmaError,
                            dma_fetch,
                        )
                        from gofr_tpu.serving.lifecycle import Deadline

                        handle = handle_from_wire(body_bytes)
                        fetch_s = float(self.config.get_or_default(
                            "TPU_DMA_FETCH_TIMEOUT_S", "5.0"
                        ))
                        try:
                            payload = dma_fetch(
                                handle,
                                deadline=Deadline.after(fetch_s),
                            )
                        except DmaError as exc:
                            return Response(
                                status=200,
                                headers={
                                    "Content-Type": "application/json"
                                },
                                body=_json.dumps({
                                    "result": "stale",
                                    "kind": exc.kind,
                                    "error": str(exc),
                                }).encode(),
                            )
                    else:
                        payload = payload_from_wire(body_bytes)
                except Exception as exc:  # noqa: BLE001 — ANY malformed body is a 400 rejection, never a 5xx
                    return Response(
                        status=400,
                        headers={"Content-Type": "application/json"},
                        body=_json.dumps({
                            "result": "rejected", "error": str(exc),
                        }).encode(),
                    )
                eng = container.tpu
                fn = getattr(eng, "import_payload", None)
                result = fn(payload) if callable(fn) else "rejected"
                return Response(
                    status=200,
                    headers={"Content-Type": "application/json"},
                    body=_json.dumps({
                        "result": result,
                        "blocks": payload.n_blocks,
                    }).encode(),
                )
            if path == "/ops/tier-export":
                # The tier-import codec in REVERSE: a remote decode pod
                # asks THIS pod for the KV blocks of a prompt prefix it
                # is about to prefill (docs/advanced-guide/
                # resilience.md "Multi-host disaggregation"). POST a
                # JSON body {"token_ids": [...], "mode": "dma"|"wire",
                # "timeout_s": n} (or GET with ?token_ids=1,2,3&mode=)
                # and the reply is a KVH1 claim ticket (mode=dma, dma
                # available), a KVB1 inline payload (mode=wire or dma
                # unavailable), or JSON {"result": "miss"} — misses and
                # unsupported engines are 200s: "prefill it yourself"
                # is a normal answer, not an error.
                import json as _json

                if raw.method == "POST":
                    try:
                        spec = _json.loads(raw.body or b"{}")
                        ids = [int(t) for t in spec["token_ids"]]
                    except Exception:  # noqa: BLE001 — ANY malformed body is a 400, never a 5xx
                        return Response(
                            status=400,
                            headers={"Content-Type": "application/json"},
                            body=b'{"error": "POST JSON with '
                                 b'token_ids: [int, ...]"}',
                        )
                elif raw.method == "GET":
                    import urllib.parse

                    q = urllib.parse.parse_qs(
                        raw.target.partition("?")[2]
                    )
                    try:
                        ids = [
                            int(t)
                            for t in q.get("token_ids", [""])[0].split(",")
                            if t
                        ]
                    except ValueError:
                        return Response(
                            status=400,
                            headers={"Content-Type": "application/json"},
                            body=b'{"error": "token_ids must be '
                                 b'comma-separated integers"}',
                        )
                    spec = {"mode": q.get("mode", ["wire"])[0]}
                else:
                    return Response(
                        status=405,
                        headers={"Allow": "GET, POST"},
                        body=b'{"error": "GET or POST"}',
                    )
                mode = str(spec.get("mode", "wire"))
                try:
                    timeout_s = min(
                        10.0, max(0.1, float(spec.get("timeout_s", 2.0)))
                    )
                except (TypeError, ValueError):
                    timeout_s = 2.0
                eng = container.tpu
                fn = getattr(eng, "export_cached", None)
                if not ids or not callable(fn):
                    return Response(
                        status=200,
                        headers={"Content-Type": "application/json"},
                        body=b'{"result": "unsupported"}',
                    )
                payload = fn(ids, timeout_s=timeout_s)
                if payload is None:
                    return Response(
                        status=200,
                        headers={"Content-Type": "application/json"},
                        body=b'{"result": "miss"}',
                    )
                from gofr_tpu.ops.kv_cache import (
                    handle_to_wire,
                    payload_to_wire,
                )

                if mode == "dma":
                    # Stage the bytes on this pod's transfer server and
                    # reply with the tiny claim ticket; the caller
                    # fetches the body over the dedicated data socket.
                    # Staging trouble degrades to the inline wire body
                    # — same bytes, one rung down.
                    try:
                        from gofr_tpu.service.dma import (
                            get_transfer_server,
                        )

                        handle = get_transfer_server().offer(
                            payload, src=str(getattr(
                                eng, "model_name", ""
                            )),
                        )
                        return Response(
                            status=200,
                            headers={
                                "Content-Type":
                                    "application/octet-stream",
                            },
                            body=handle_to_wire(handle),
                        )
                    except Exception:  # noqa: BLE001 — dma staging failure degrades to the wire body
                        pass
                return Response(
                    status=200,
                    headers={
                        "Content-Type": "application/octet-stream",
                    },
                    body=payload_to_wire(payload),
                )
            if path == "/debug/tpu-trace":
                import asyncio as _aio
                import json as _json
                import urllib.parse

                q = urllib.parse.parse_qs(raw.target.partition("?")[2])
                try:
                    ms = min(int(q.get("ms", ["1000"])[0]), 30_000)
                except ValueError:
                    return Response(
                        status=400,
                        headers={"Content-Type": "application/json"},
                        body=b'{"error": "ms must be an integer"}',
                    )
                # The process-wide capture singleton (serving/
                # profiler_capture.py): ONE reusable trace dir (each
                # capture overwrites the last — an unauthenticated loop
                # of trace requests must not fill the disk) and ONE
                # lock, both created at singleton construction under a
                # module lock — the old lazy `hasattr` init here let
                # two concurrent first requests mint two dirs/locks and
                # trace concurrently. Shared with the scheduler-loop
                # profiler's stall-triggered captures, so a manual
                # capture and an anomaly capture can never overlap.
                from gofr_tpu.serving.profiler_capture import get_capture

                cap = get_capture()
                if not cap.try_acquire():
                    return Response(
                        status=409,
                        headers={"Content-Type": "application/json"},
                        body=b'{"error": "a trace capture is already '
                             b'running"}',
                    )
                try:
                    loop = _aio.get_running_loop()
                    try:
                        # start/stop serialize trace data to disk — keep
                        # them off the event loop that also serves
                        # /metrics and liveness probes.
                        await loop.run_in_executor(None, cap.start_trace)
                        await _aio.sleep(ms / 1e3)
                        await loop.run_in_executor(None, cap.stop_trace)
                        cap.note_manual_capture()
                        body = {
                            "trace_dir": cap.trace_dir,
                            "captured_ms": ms,
                        }
                        status = 200
                    except Exception as exc:  # noqa: BLE001 — debug surface
                        body = {"error": str(exc)}
                        status = 500
                finally:
                    cap.release()
                return Response(
                    status=status,
                    headers={"Content-Type": "application/json"},
                    body=_json.dumps(body).encode(),
                )
            return Response(status=404, headers={}, body=b"404 page not found")

        return handler


def new_cmd(config_dir: str = "./configs"):
    """CLI app factory (reference ``gofr.go:99-111``)."""
    from gofr_tpu.cli import CMDApp

    return CMDApp(config_dir=config_dir)
