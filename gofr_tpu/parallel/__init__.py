"""Parallelism layer (net-new; SURVEY §2.6).

The reference's "distributed backend" is service networking; here the
intra-pod story is XLA collectives compiled in by GSPMD: pick a
``jax.sharding.Mesh``, annotate param/activation shardings, jit. Axes:

* ``dp`` — data parallel (batch);
* ``tp`` — tensor parallel (attention heads / FFN hidden / vocab), also
  carrying sequence-parallel activations and expert-parallel MoE weights;
* ``pp`` — pipeline stages (``gofr_tpu.parallel.pipeline``).

Cross-host (DCN) coordination reuses the service tier (SURVEY §2.6 "DCN
tier") — jax.distributed for the runtime, the framework's HTTP client for
app-level routing.
"""

from gofr_tpu.parallel.mesh import (
    make_mesh,
    mesh_axis_sizes,
    mesh_topology,
    partition_devices,
)
from gofr_tpu.parallel.sharding import shard_pytree, make_train_step
from gofr_tpu.parallel.pipeline import pipeline_layer_fn, pipeline_spmd
from gofr_tpu.parallel.dcn import initialize_multihost, process_topology

__all__ = [
    "make_mesh",
    "mesh_axis_sizes",
    "mesh_topology",
    "partition_devices",
    "shard_pytree",
    "make_train_step",
    "pipeline_layer_fn",
    "pipeline_spmd",
    "initialize_multihost",
    "process_topology",
]
