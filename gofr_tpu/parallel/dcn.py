"""DCN tier: multi-host runtime init + cross-host coordination seams.

SURVEY §2.6: intra-pod scaling is compiled XLA collectives over ICI
(``parallel/sharding.py``); the cross-host (DCN) tier has two parts:

* **Runtime**: ``jax.distributed`` — every host runs the same program,
  one coordinator, and ``jax.devices()`` becomes the global device set so
  meshes (and the collectives compiled over them) span hosts. This module
  wraps the init with the framework's env-config idiom.
* **App-level routing** reuses the service tier verbatim — the
  inter-service HTTP client + circuit breaker (``gofr_tpu/service``) is
  the cross-pod request path, exactly how the reference treats
  cross-service communication.

Config keys: ``DCN_COORDINATOR`` (host:port of process 0),
``DCN_NUM_PROCESSES``, ``DCN_PROCESS_ID``. Absent config → single-host
no-op, so the same binary runs laptop and pod.
"""

from __future__ import annotations

from typing import Optional


def initialize_multihost(
    config=None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    logger=None,
) -> bool:
    """Initialize the multi-host JAX runtime; returns True if distributed.

    Explicit args win over ``config`` keys. With neither, this is a no-op
    (single host) — boot code can call it unconditionally.
    """
    if config is not None:
        coordinator_address = coordinator_address or config.get_or_default(
            "DCN_COORDINATOR", ""
        )
        if num_processes is None:
            n = config.get_or_default("DCN_NUM_PROCESSES", "")
            num_processes = int(n) if n else None
        if process_id is None:
            p = config.get_or_default("DCN_PROCESS_ID", "")
            process_id = int(p) if p else None
    if not coordinator_address:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    if logger is not None:
        logger.infof(
            "multi-host runtime up: process %s/%s via %s — %d global devices",
            jax.process_index(), jax.process_count(), coordinator_address,
            len(jax.devices()),
        )
    return True


def process_topology() -> dict:
    """Host-level topology for health/diagnostics endpoints."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }
