"""SPMD pipeline parallelism over a ``pp`` mesh axis.

Net-new (SURVEY §2.6 — the reference has no model code). GPipe-style
schedule expressed the TPU way: every stage is the *same* compiled program
(one ``shard_map`` body), stacked layer params are sharded over ``pp`` on
their leading (layer) axis, and activations hop stage→stage with
``lax.ppermute`` — nearest-neighbour ICI traffic, no host involvement.

The schedule runs ``M + S - 1`` ticks for M microbatches over S stages
(the usual GPipe bubble). Each tick: stage 0 feeds the next microbatch,
every stage applies its local slice of layers, the result hops forward.
Because the tick loop is a static-bound ``fori_loop``, XLA compiles ONE
tick body and the whole pipeline — including its backward pass, which JAX
derives through the loop and the ppermutes — stays a single jitted program.

Composition with other axes: the ``shard_map`` is *partial-manual* (only
``pp`` is manual), so dp/tp/sp shardings keep flowing through the stage
body under GSPMD as usual.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_spmd(
    x: jnp.ndarray,
    stage_params: Any,
    extras: Any,
    *,
    axis_name: str,
    n_microbatches: int,
    stage_fn: Callable[[jnp.ndarray, Any, Any], jnp.ndarray],
) -> jnp.ndarray:
    """GPipe schedule; call inside ``shard_map`` with ``axis_name`` manual.

    x: [b, ...] full batch (b % n_microbatches == 0); stage_params: this
    stage's slice of the stacked layer params (leading layer axis sharded
    over ``axis_name`` outside); extras: replicated side inputs handed to
    every ``stage_fn`` call; stage_fn(act, stage_params, extras) -> act.

    Returns [b, ...] — the last stage's outputs, made uniform across the
    axis with one psum so downstream (final norm / head) code is ordinary.
    """
    S = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = n_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by n_microbatches={M}")
    x_micro = x.reshape(M, b // M, *x.shape[1:])

    recv = jnp.zeros_like(x_micro[0])
    outputs = jnp.zeros_like(x_micro)
    recv, outputs = (
        lax.pcast(t, axis_name, to="varying") for t in (recv, outputs)
    )
    # Forward hop i → i+1. The wraparound edge (last → 0) only carries
    # values stage 0 never reads — it always feeds from x_micro.
    perm = [(j, (j + 1) % S) for j in range(S)]

    def tick(t, carry):
        recv, outputs = carry
        feed = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        act_in = jnp.where(idx == 0, feed, recv)
        act_out = stage_fn(act_in, stage_params, extras)
        # Microbatch t reaches the last stage at tick t + S - 1.
        out_idx = t - (S - 1)
        upd = lax.dynamic_update_index_in_dim(
            outputs, act_out, jnp.clip(out_idx, 0, M - 1), 0
        )
        outputs = jnp.where(out_idx >= 0, upd, outputs)
        # Final tick's hop would be discarded — skip it (uniform predicate).
        recv = lax.cond(
            t < M + S - 2,
            lambda a: lax.ppermute(a, axis_name, perm),
            lambda a: a,
            act_out,
        )
        return recv, outputs

    _, outputs = lax.fori_loop(0, M + S - 1, tick, (recv, outputs))
    outputs = jnp.where(idx == S - 1, outputs, 0.0)
    # XLA:CPU's AllReducePromotion pass crashes on bf16 all-reduce
    # (hlo_instruction.cc "Invalid binary instruction opcode copy"), so the
    # virtual-device path psums in f32; TPU keeps the bf16 ICI transfer.
    dtype = outputs.dtype
    if dtype == jnp.bfloat16 and jax.default_backend() != "tpu":
        outputs = lax.psum(outputs.astype(jnp.float32), axis_name).astype(dtype)
    else:
        outputs = lax.psum(outputs, axis_name)
    return outputs.reshape(b, *x.shape[1:])


def pipeline_layer_fn(
    layers_fn: Callable[[jnp.ndarray, Any, Any], jnp.ndarray],
    mesh: Mesh,
    *,
    axis_name: str = "pp",
    n_microbatches: int = 4,
) -> Callable[[jnp.ndarray, Any, Any], jnp.ndarray]:
    """Wrap a per-layer-stack function into a pipelined one over ``mesh``.

    ``layers_fn(x, stacked_layer_params, extras)`` must scan its local layer
    stack (leading axis = layers). The returned callable takes *global*
    arrays — stacked params over the full depth — and runs them pipelined
    over ``mesh[axis_name]``; every other mesh axis stays auto (GSPMD).
    """

    def run(x, layer_params, extras):
        inner = lambda x, lp, ex: pipeline_spmd(  # noqa: E731
            x, lp, ex,
            axis_name=axis_name,
            n_microbatches=n_microbatches,
            stage_fn=layers_fn,
        )
        layer_specs = jax.tree_util.tree_map(lambda _: P(axis_name), layer_params)
        extra_specs = jax.tree_util.tree_map(lambda _: P(), extras)
        return jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(), layer_specs, extra_specs),
            out_specs=P(),
            axis_names={axis_name},
        )(x, layer_params, extras)

    return run
