"""Device mesh construction.

``make_mesh({"dp": 2, "tp": 4})`` reshapes the visible devices into a named
:class:`jax.sharding.Mesh`. Axis order follows the dict order — put the
fastest-varying (innermost, highest-bandwidth ICI) axis last, which is where
``tp`` belongs on a TPU slice.
"""

from __future__ import annotations

import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axes: Mapping[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    want = math.prod(axes.values())
    if want > len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {want} devices, have {len(devices)}"
        )
    grid = np.array(devices[:want]).reshape(tuple(axes.values()))
    return Mesh(grid, tuple(axes.keys()))


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def mesh_topology(mesh: Optional[Mesh]) -> Optional[dict]:
    """JSON-able descriptor of a serving mesh — what health probes,
    ``/debug/flight`` replica records, and pool descriptors advertise so
    an operator can see each replica's pod shape without shelling into
    it. ``None`` for an unsharded (single-chip) engine."""
    if mesh is None:
        return None
    return {
        "axes": mesh_axis_sizes(mesh),
        "n_devices": int(mesh.devices.size),
        "devices": [str(d) for d in mesh.devices.flat],
    }


def partition_devices(
    devices: Sequence, group_size: int, n_groups: int
) -> list[list]:
    """Split ``devices`` into ``n_groups`` disjoint groups of
    ``group_size`` — the replica-pool pod layout (dp across replicas, tp
    within each). When the device count cannot cover every group
    disjointly (e.g. in-proc replicas on one real TPU slice), every
    group past the last full slice shares the FIRST group's devices:
    correctness is unaffected (each engine jits its own programs), only
    the parallel-speedup claim weakens, which the caller should log.
    Fewer devices than ONE group is an error — an undersized group
    would fail later inside ``make_mesh`` with misleading context."""
    if group_size <= 0:
        raise ValueError(f"group_size must be positive, got {group_size}")
    devices = list(devices)
    if len(devices) < group_size:
        raise ValueError(
            f"cannot carve a {group_size}-device group from "
            f"{len(devices)} device(s)"
        )
    groups: list[list] = []
    for i in range(n_groups):
        lo, hi = i * group_size, (i + 1) * group_size
        if hi <= len(devices):
            groups.append(devices[lo:hi])
        else:
            groups.append(devices[:group_size])
    return groups
