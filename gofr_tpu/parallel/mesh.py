"""Device mesh construction.

``make_mesh({"dp": 2, "tp": 4})`` reshapes the visible devices into a named
:class:`jax.sharding.Mesh`. Axis order follows the dict order — put the
fastest-varying (innermost, highest-bandwidth ICI) axis last, which is where
``tp`` belongs on a TPU slice.
"""

from __future__ import annotations

import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axes: Mapping[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    want = math.prod(axes.values())
    if want > len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {want} devices, have {len(devices)}"
        )
    grid = np.array(devices[:want]).reshape(tuple(axes.values()))
    return Mesh(grid, tuple(axes.keys()))


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
