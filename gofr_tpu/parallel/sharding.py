"""Sharded placement + the sharded training step.

The scaling-book recipe made concrete: params get NamedShardings from the
model's partition specs, the batch shards over ``dp``, activations carry
sequence-parallel constraints over ``tp``, and one ``jax.jit`` with
donate/out shardings compiles the whole update — XLA inserts the
all-reduces/all-gathers over ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put every leaf with the NamedSharding from its matching spec."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def named_shardings(specs: Any, mesh: Mesh) -> Any:
    """Map a PartitionSpec pytree to NamedShardings on ``mesh`` (the shared
    idiom for jit in/out_shardings and device_put placement)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def prune_specs(specs: Any, mesh: Mesh) -> Any:
    """Drop axis names a mesh doesn't have from a PartitionSpec pytree.

    Lets one canonical spec set (mentioning dp/tp/pp/…) serve any mesh —
    a {"dp","pp"} mesh simply replicates the tp-annotated dims.
    """
    axes = set(mesh.axis_names)

    def prune(spec):
        out = []
        for entry in spec:
            if entry is None:
                out.append(None)
            elif isinstance(entry, tuple):
                kept = tuple(a for a in entry if a in axes)
                out.append(kept if kept else None)
            else:
                out.append(entry if entry in axes else None)
        return P(*out)

    return jax.tree_util.tree_map(
        prune, specs, is_leaf=lambda x: isinstance(x, P)
    )


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross entropy. logits [b, s, V] f32, targets [b, s]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    tgt_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(tgt_logp)


def make_train_step(
    cfg,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    sp: bool = True,
    remat: bool = False,
    cp_impl: str = "ring",
    n_microbatches: int = 2,
) -> tuple[Callable, Callable, optax.GradientTransformation]:
    """Build (init_state, train_step) for the flagship transformer over
    ``mesh``. Parallelism comes from the mesh's axis names:

    * ``dp`` — batch sharding;
    * ``tp`` — Megatron tensor parallel (+ sequence-parallel activation
      constraints when ``sp``; MoE expert weights ride ``tp`` too);
    * ``cp`` — context parallelism: the sequence dim shards over ``cp`` and
      attention runs as ring/Ulysses collectives (``cp_impl``), see
      ``ops/ring_attention.py``;
    * ``pp`` — GPipe pipeline over the stacked layer axis with
      ``n_microbatches`` microbatches, see ``parallel/pipeline.py``.

    Returns ``(init_state_fn, train_step_fn, optimizer)``:
    ``init_state_fn(key) -> (params, opt_state)`` sharded onto the mesh;
    ``train_step_fn(params, opt_state, tokens) -> (loss, params, opt_state)``
    jitted with donated state.
    """
    from gofr_tpu.models.transformer import (
        _embed,
        _layer_prefill,
        _norm,
        init_transformer,
        transformer_param_specs,
    )
    from gofr_tpu.ops.rotary import rope_frequencies
    from gofr_tpu.parallel.mesh import mesh_axis_sizes

    axes = mesh_axis_sizes(mesh)
    use_pp = axes.get("pp", 1) > 1
    use_cp = axes.get("cp", 1) > 1

    optimizer = optax.adamw(learning_rate)
    param_specs = prune_specs(transformer_param_specs(cfg, pp=use_pp), mesh)

    attn_fn = None
    if use_cp and not use_pp:
        from gofr_tpu.ops.ring_attention import context_parallel_attention

        def attn_fn(q, k, v, mask):
            assert mask is None, "cp training path has no padding mask"
            return context_parallel_attention(
                q, k, v, mesh, axis_name="cp", impl=cp_impl
            )
    # pp + cp: the ring/Ulysses implementations open their own shard_map,
    # which cannot nest inside the pipeline's manual-pp region — but the
    # pipeline's shard_map is PARTIAL-manual (only pp), so cp composes as
    # a GSPMD auto axis instead: activations stay seq-sharded over cp and
    # the dense causal attention's softmax reductions compile to cp
    # collectives (the serving cp path's formulation). Costs an allgather
    # of K/V over cp inside attention where the ring overlaps it — the
    # composition is for capacity (layers over pp, sequence over cp), not
    # peak attention overlap.

    # Mixed precision: master params live in f32 (stable AdamW moments, f32
    # grad all-reduces); compute runs in cfg.dtype so the MXU sees bf16.
    # XLA:CPU exception: its AllReducePromotion pass aborts on the bf16
    # all-reduces a manual-pp program produces ("Invalid binary instruction
    # opcode copy"), so the virtual-device pp path computes in f32 — the
    # shardings exercised are identical, only the dtype differs.
    compute_dtype = cfg.dtype
    if use_pp and jax.default_backend() != "tpu":
        compute_dtype = jnp.float32

    def _to_compute(params):
        return jax.tree_util.tree_map(
            lambda x: x.astype(compute_dtype)
            if x.dtype in (jnp.float32, jnp.bfloat16)
            else x,
            params,
        )

    def forward(params, tokens):
        params = _to_compute(params)
        b, s = tokens.shape
        positions = jnp.arange(s)[None, :]  # [1, s], broadcasts over batch
        x = _embed(params, tokens, cfg, positions)
        cos, sin = rope_frequencies(cfg.rope_dims, s, cfg.rope_theta)

        def constrain(h):
            if use_cp:
                seq_ax = ("cp", "tp") if sp else "cp"
            else:
                # Sequence-parallel residual stream: tokens sharded over tp
                # between attention/FFN blocks (Megatron-SP shape).
                seq_ax = "tp" if sp else None
            spec = prune_specs(P("dp", seq_ax, None), mesh)
            if use_pp:
                # Inside the pipeline's manual-pp region activations carry a
                # vma over pp; a full-mesh NamedSharding conflicts with it,
                # but a bare PartitionSpec resolves against the context mesh.
                return jax.lax.with_sharding_constraint(h, spec)
            return jax.lax.with_sharding_constraint(h, NamedSharding(mesh, spec))

        def gather_seq(h):
            # Megatron-SP block boundary: all-gather the tp part of the
            # sequence sharding on each block's normed input, so the
            # tp-sharded projection weights alone determine q/k/v head
            # shardings — without this, RoPE's concat on k sits on a
            # seq→kv-head reshard GSPMD can only do by involuntary full
            # rematerialization when n_kv_heads < tp (the r3 dryrun
            # spmd_partitioner warnings). cp's seq sharding stays put.
            if not sp:
                return h
            spec = prune_specs(P("dp", "cp" if use_cp else None, None), mesh)
            if use_pp:
                return jax.lax.with_sharding_constraint(h, spec)
            return jax.lax.with_sharding_constraint(h, NamedSharding(mesh, spec))

        def make_body(cos, sin, positions):
            # One definition serves both the plain scan and the pipeline
            # stage scan; RoPE tables come in as args because shard_map
            # bodies must not close over tracers.
            def body(x, lp):
                out, _ = _layer_prefill(
                    x, lp, cfg, cos, sin, positions, mask=None,
                    attn_fn=attn_fn, norm_out=gather_seq,
                )
                return constrain(out), None

            return jax.checkpoint(body) if remat else body

        if use_pp:
            from gofr_tpu.parallel.pipeline import pipeline_layer_fn

            def layers_fn(act, lp_stack, extras):
                act, _ = jax.lax.scan(make_body(*extras), act, lp_stack)
                return act

            run = pipeline_layer_fn(
                layers_fn, mesh, axis_name="pp", n_microbatches=n_microbatches
            )
            x = run(x, params["layers"], (cos, sin, positions))
        else:
            x = constrain(x)
            x, _ = jax.lax.scan(make_body(cos, sin, positions), x, params["layers"])
        x = _norm(x, params["final_norm"], cfg, params.get("final_norm_b"))
        return jnp.einsum("bsd,dv->bsv", x, params["lm_head"]).astype(jnp.float32)

    def loss_fn(params, tokens):
        # Forward over the full sequence (keeps the seq dim divisible by
        # cp/tp shards); the next-token shift happens at the loss.
        logits = forward(params, tokens)
        return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    def _opt_specs(params_specs):
        # AdamW state embeds copies of the param tree (mu/nu); any subtree of
        # the opt state that IS the param tree gets the param specs
        # leaf-for-leaf (matched structurally, not by shape — wq/wo have
        # identical shapes but transposed shardings). Scalars replicate.
        sample_params = jax.eval_shape(lambda k: init_transformer(k, cfg),
                                       jax.random.PRNGKey(0))
        opt_shape = jax.eval_shape(optimizer.init, sample_params)
        params_treedef = jax.tree_util.tree_structure(sample_params)

        def is_param_tree(x):
            try:
                return jax.tree_util.tree_structure(x) == params_treedef
            except Exception:
                return False

        children, treedef = jax.tree_util.tree_flatten(
            opt_shape, is_leaf=is_param_tree
        )
        mapped = [params_specs if is_param_tree(c) else P() for c in children]
        return jax.tree_util.tree_unflatten(treedef, mapped)

    opt_specs = _opt_specs(param_specs)
    param_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    opt_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), opt_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    data_sharding = NamedSharding(
        mesh, prune_specs(P("dp", "cp" if use_cp else None), mesh)
    )

    def _init_master(key):
        params = init_transformer(key, cfg)
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32)
            if x.dtype == jnp.bfloat16
            else x,
            params,
        )

    init_jit = jax.jit(_init_master, out_shardings=param_shardings)
    opt_init_jit = jax.jit(optimizer.init, out_shardings=opt_shardings)

    def init_state(key):
        params = init_jit(key)
        opt_state = opt_init_jit(params)
        return params, opt_state

    step_jit = jax.jit(
        train_step,
        in_shardings=(param_shardings, opt_shardings, data_sharding),
        out_shardings=(NamedSharding(mesh, P()), param_shardings, opt_shardings),
        donate_argnums=(0, 1),
    )
    return init_state, step_jit, optimizer


def make_lora_train_step(
    cfg,
    base_params: Any,
    rank: int,
    targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
    mesh: Optional[Mesh] = None,
    learning_rate: float = 1e-3,
) -> tuple[Callable, Callable]:
    """Adapter fine-tuning on a FROZEN base: the train→serve loop for
    multi-LoRA (train here, then ``engine.load_lora(name, leaves)``).

    Only the LoRA factors train — AdamW state is O(rank), and the frozen
    base may be int8/int4-quantized (QLoRA shape: the ``_wein`` base
    matmul dequantizes on the fly; deltas add after it, and no gradient
    flows into the quantized leaves). Trainable leaves are kept in the
    exact raw-dict form ``load_lora`` accepts: ``{target: (a [L, d_in,
    r] f32, b [L, r, d_out] f32)}``; standard init (a ~ N(0, 1/r),
    b = 0) makes step 0 exactly the base model.

    Under a mesh, factors shard like their base projections minus the
    adapter axis (column-parallel targets shard b's output dim over
    ``tp``; row-parallel a's input dim) and the batch shards over ``dp``.
    Returns ``(init_lora_state, lora_train_step)``:
    ``init_lora_state(key) -> (lora, opt_state)``;
    ``lora_train_step(lora, opt_state, tokens) -> (loss, lora,
    opt_state)``.
    """
    from gofr_tpu.models.transformer import (
        LORA_TARGETS,
        lora_dims,
        lora_param_specs,
        transformer_forward,
    )

    # Mirror init_lora's guards: on a MoE base the FFN routes through
    # _ffn_moe, which has no adapter path — FFN factors would train as
    # silent no-ops (zero gradient) and be unservable anyway.
    if cfg.is_moe:
        raise ValueError("LoRA training does not support MoE models")
    for t in targets:
        if t not in LORA_TARGETS:
            raise ValueError(
                f"unknown LoRA target {t!r} (of {LORA_TARGETS})"
            )

    optimizer = optax.adamw(learning_rate)

    def _merged(lora):
        # Splice the trainable factors into the base tree with a
        # 1-adapter axis; aids=0 then selects them for every row. The
        # per-step stack is rank-sized — noise next to the forward.
        layers = dict(base_params["layers"])
        for t in targets:
            a, b = lora[t]
            layers[t + "_lora_a"] = a[:, None].astype(cfg.dtype)
            layers[t + "_lora_b"] = b[:, None].astype(cfg.dtype)
        return {**base_params, "layers": layers}

    def loss_fn(lora, tokens):
        aids = jnp.zeros((tokens.shape[0],), dtype=jnp.int32)
        logits = transformer_forward(_merged(lora), tokens, cfg, aids=aids)
        return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])

    def train_step(lora, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(lora, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        return loss, lora, opt_state

    def _init(key):
        lora = {}
        for t in targets:
            d_in, d_out = lora_dims(cfg, t)
            key, k1 = jax.random.split(key)
            lora[t] = (
                jax.random.normal(k1, (cfg.n_layers, d_in, rank)) / rank,
                jnp.zeros((cfg.n_layers, rank, d_out), dtype=jnp.float32),
            )
        return lora

    if mesh is None:
        step_jit = jax.jit(train_step, donate_argnums=(0, 1))

        def init_state(key):
            lora = jax.jit(_init)(key)
            return lora, optimizer.init(lora)

        return init_state, step_jit

    full = lora_param_specs(targets)
    lora_specs = {
        t: (
            P(*(s for i, s in enumerate(full[t + "_lora_a"]) if i != 1)),
            P(*(s for i, s in enumerate(full[t + "_lora_b"]) if i != 1)),
        )
        for t in targets
    }
    lora_specs = prune_specs(lora_specs, mesh)
    lora_sh = named_shardings(lora_specs, mesh)
    sample = jax.eval_shape(_init, jax.random.PRNGKey(0))
    opt_shape = jax.eval_shape(optimizer.init, sample)
    lora_treedef = jax.tree_util.tree_structure(sample)

    def is_lora_tree(x):
        try:
            return jax.tree_util.tree_structure(x) == lora_treedef
        except Exception:
            return False

    children, treedef = jax.tree_util.tree_flatten(
        opt_shape, is_leaf=is_lora_tree
    )
    opt_sh = jax.tree_util.tree_unflatten(
        treedef,
        [
            lora_sh if is_lora_tree(c) else NamedSharding(mesh, P())
            for c in children
        ],
    )
    data_sh = NamedSharding(mesh, prune_specs(P("dp", None), mesh))
    step_jit = jax.jit(
        train_step,
        in_shardings=(lora_sh, opt_sh, data_sh),
        out_shardings=(NamedSharding(mesh, P()), lora_sh, opt_sh),
        donate_argnums=(0, 1),
    )
    init_jit = jax.jit(_init, out_shardings=lora_sh)
    opt_init_jit = jax.jit(optimizer.init, out_shardings=opt_sh)

    def init_state(key):
        lora = init_jit(key)
        return lora, opt_init_jit(lora)

    return init_state, step_jit

