"""Sharded placement + the sharded training step.

The scaling-book recipe made concrete: params get NamedShardings from the
model's partition specs, the batch shards over ``dp``, activations carry
sequence-parallel constraints over ``tp``, and one ``jax.jit`` with
donate/out shardings compiles the whole update — XLA inserts the
all-reduces/all-gathers over ICI.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put every leaf with the NamedSharding from its matching spec."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross entropy. logits [b, s, V] f32, targets [b, s]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    tgt_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(tgt_logp)


def make_train_step(
    cfg,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    sp: bool = True,
    remat: bool = False,
) -> tuple[Callable, Callable, optax.GradientTransformation]:
    """Build (init_state, train_step) for the flagship transformer over
    ``mesh`` with dp/tp (+sequence-parallel activations, +expert-parallel
    MoE weights when the config has experts).

    Returns ``(init_state_fn, train_step_fn, optimizer)``:
    ``init_state_fn(key) -> (params, opt_state)`` sharded onto the mesh;
    ``train_step_fn(params, opt_state, tokens) -> (loss, params, opt_state)``
    jitted with donated state.
    """
    from gofr_tpu.models.transformer import (
        init_transformer,
        transformer_param_specs,
        _layer_prefill,
    )
    from gofr_tpu.ops.norms import rms_norm
    from gofr_tpu.ops.rotary import rope_frequencies

    optimizer = optax.adamw(learning_rate)
    param_specs = transformer_param_specs(cfg)

    def forward(params, tokens):
        b, s = tokens.shape
        x = params["embed"][tokens]
        cos, sin = rope_frequencies(cfg.head_dim, s, cfg.rope_theta)
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))

        def constrain(h):
            if sp:
                # Sequence-parallel residual stream: tokens sharded over tp
                # between attention/FFN blocks (Megatron-SP shape).
                return jax.lax.with_sharding_constraint(
                    h, NamedSharding(mesh, P("dp", "tp", None))
                )
            return jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, P("dp", None, None))
            )

        def body(x, lp):
            out, _ = _layer_prefill(x, lp, cfg, cos, sin, positions, mask=None)
            return constrain(out), None

        if remat:
            body = jax.checkpoint(body)
        x = constrain(x)
        x, _ = jax.lax.scan(body, x, params["layers"])
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        return jnp.einsum("bsd,dv->bsv", x, params["lm_head"]).astype(jnp.float32)

    def loss_fn(params, tokens):
        logits = forward(params, tokens[:, :-1])
        return cross_entropy_loss(logits, tokens[:, 1:])

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    def _opt_specs(params_specs):
        # AdamW state embeds copies of the param tree (mu/nu); any subtree of
        # the opt state that IS the param tree gets the param specs
        # leaf-for-leaf (matched structurally, not by shape — wq/wo have
        # identical shapes but transposed shardings). Scalars replicate.
        sample_params = jax.eval_shape(lambda k: init_transformer(k, cfg),
                                       jax.random.PRNGKey(0))
        opt_shape = jax.eval_shape(optimizer.init, sample_params)
        params_treedef = jax.tree_util.tree_structure(sample_params)

        def is_param_tree(x):
            try:
                return jax.tree_util.tree_structure(x) == params_treedef
            except Exception:
                return False

        children, treedef = jax.tree_util.tree_flatten(
            opt_shape, is_leaf=is_param_tree
        )
        mapped = [params_specs if is_param_tree(c) else P() for c in children]
        return jax.tree_util.tree_unflatten(treedef, mapped)

    opt_specs = _opt_specs(param_specs)
    param_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    opt_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), opt_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    data_sharding = NamedSharding(mesh, P("dp", None))

    init_jit = jax.jit(
        lambda key: init_transformer(key, cfg), out_shardings=param_shardings
    )
    opt_init_jit = jax.jit(optimizer.init, out_shardings=opt_shardings)

    def init_state(key):
        params = init_jit(key)
        opt_state = opt_init_jit(params)
        return params, opt_state

    step_jit = jax.jit(
        train_step,
        in_shardings=(param_shardings, opt_shardings, data_sharding),
        out_shardings=(NamedSharding(mesh, P()), param_shardings, opt_shardings),
        donate_argnums=(0, 1),
    )
    return init_state, step_jit, optimizer
