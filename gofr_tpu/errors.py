"""Framework error types.

Mirrors the reference's HTTP error vocabulary (``pkg/gofr/http/errors.go``):
typed errors that carry their HTTP status so the responder can map
error → status without stringly-typed checks. Any exception exposing a
``status_code`` attribute is honored by the responder
(reference ``http/responder.go:53-74``).
"""

from __future__ import annotations

from typing import Sequence


class GofrError(Exception):
    """Base class; responder maps subclasses via ``status_code``."""

    status_code: int = 500


class ErrorEntityNotFound(GofrError):
    """404 — entity lookup miss (reference ``http/errors.go`` EntityNotFound)."""

    status_code = 404

    def __init__(self, name: str, value: str) -> None:
        super().__init__(f"No entity found with {name}: {value}")
        self.name = name
        self.value = value


class ErrorEntityAlreadyExists(GofrError):
    status_code = 409

    def __init__(self) -> None:
        super().__init__("entity already exists")


class ErrorInvalidParam(GofrError):
    """400 — invalid parameter(s)."""

    status_code = 400

    def __init__(self, params: Sequence[str] = ()) -> None:
        self.params = list(params)
        count = len(self.params)
        super().__init__(f"'{count}' invalid parameter(s): {', '.join(self.params)}")


class ErrorMissingParam(GofrError):
    status_code = 400

    def __init__(self, params: Sequence[str] = ()) -> None:
        self.params = list(params)
        count = len(self.params)
        super().__init__(f"'{count}' missing parameter(s): {', '.join(self.params)}")


class ErrorInvalidRoute(GofrError):
    status_code = 404

    def __init__(self) -> None:
        super().__init__("route not registered")


class ErrorRequestTimeout(GofrError):
    status_code = 408

    def __init__(self) -> None:
        super().__init__("request timed out")


class ErrorPanicRecovery(GofrError):
    """500 — handler raised an unexpected exception
    (reference ``http/middleware/logger.go:121-146``)."""

    status_code = 500

    def __init__(self) -> None:
        super().__init__("some unexpected error has occurred")


class ErrorServiceUnavailable(GofrError):
    status_code = 503

    def __init__(self, dependency: str = "") -> None:
        msg = "service unavailable"
        if dependency:
            msg += f": {dependency}"
        super().__init__(msg)


class ErrorPayloadTooLarge(GofrError):
    """413 — an uploaded payload exceeds a configured store limit."""

    status_code = 413

    def __init__(self, what: str, size: int, limit: int) -> None:
        super().__init__(
            f"{what} of {size} bytes exceeds the limit of {limit} bytes"
        )


class ErrorTooManyRequests(GofrError):
    """429 — the submit queue is over its token budget (load shedding).

    Carries a ``Retry-After`` estimate derived from the queue's token
    backlog over the engine's measured throughput; the responder copies
    ``headers`` onto the wire so well-behaved clients back off instead
    of hammering an overloaded engine.
    """

    status_code = 429

    def __init__(self, reason: str, retry_after_s: float = 1.0) -> None:
        self.retry_after_s = max(1, int(-(-retry_after_s // 1)))  # ceil ≥ 1
        self.headers = {"Retry-After": str(self.retry_after_s)}
        super().__init__(
            f"request shed: {reason}; retry after ~{self.retry_after_s}s"
        )


class ErrorDeadlineExceeded(GofrError):
    """504 — the request's deadline expired before (or during)
    generation. Mid-stream, the scheduler retires the sequence and
    frees its KV blocks; the stream ends with this terminal error."""

    status_code = 504

    def __init__(self, detail: str = "") -> None:
        msg = "deadline exceeded"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class ErrorRequestCancelled(GofrError):
    """499 (client closed request) — the caller cancelled or
    disconnected; the engine retired the sequence mid-decode."""

    status_code = 499

    def __init__(self) -> None:
        super().__init__("request cancelled by the client")


class ErrorNoHealthyReplica(GofrError):
    """502 — the replica pool could not place the request on ANY
    backend: every replica is DOWN/RESTARTING, demoted by a failed
    probe, or rejected the submit. 502 (bad gateway) rather than 503 on
    purpose: a single replica's drain answers 503 (retry THIS address
    later), while 502 says the routing tier itself found no healthy
    upstream — load balancers and clients treat the two differently."""

    status_code = 502

    def __init__(self, detail: str = "") -> None:
        msg = "no healthy replica available"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class ErrorPromptTooLong(GofrError):
    """413 — prompt exceeds the engine's serveable context window. A
    serving framework must surface this, not silently truncate (truncation
    is opt-in via TPU_TRUNCATE_PROMPTS)."""

    status_code = 413

    def __init__(self, prompt_tokens: int, max_tokens: int) -> None:
        self.prompt_tokens = prompt_tokens
        self.max_tokens = max_tokens
        super().__init__(
            f"prompt of {prompt_tokens} tokens exceeds the maximum "
            f"serveable prompt length {max_tokens}"
        )
