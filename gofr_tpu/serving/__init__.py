"""TPU serving stack (net-new; SURVEY §2.6).

The graft the reference never had: a JAX/XLA inference backend living in the
container like any other datasource (``TPU()`` member), a dynamic batcher
coalescing concurrent requests into padded executions, a slot-based KV cache
for autoregressive decode, per-chip observability on the framework
metrics registry, and a self-healing supervision layer
(``supervisor.py``) that warm-restarts a tripped or crashed engine and
replays its in-flight requests.
"""
