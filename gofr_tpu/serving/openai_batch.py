"""OpenAI Files + Batches API: offline batch inference over the online
surface.

``POST /v1/files`` (multipart, purpose=batch) uploads a JSONL request
file; ``POST /v1/batches`` runs every line — ``{"custom_id", "method":
"POST", "url": "/v1/chat/completions" | "/v1/completions" |
"/v1/embeddings", "body": {...}}`` — and produces OpenAI-shaped output
and error files, polled via ``GET /v1/batches/{id}`` and downloaded via
``GET /v1/files/{id}/content``.

Design: each line dispatches through the app's OWN router in-process
(the exact online code path — model/adapter routing, validation errors,
middleware spans and metrics all behave identically to a live HTTP
call), and the serving engine's continuous batching coalesces the
concurrent lines onto the chips; a bounded semaphore just keeps the
admission queue sane. This is the API-level twin of the pub/sub offline
path (``subscriber → infer → publisher``, BASELINE config 4): same
engine machinery, jobs-over-HTTP instead of jobs-over-broker.

Reference analog: none (GoFr has no async-job API); the storage shape
follows its in-memory idioms, and files/batches live in process memory
— per-replica, like the prefix pool. A 24h completion window is
accepted and ignored (batches start immediately).
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from gofr_tpu.errors import (
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorPayloadTooLarge,
)
from gofr_tpu.http.proto import RawRequest
from gofr_tpu.http.responder import File as FileResponse, Raw

_ENDPOINTS = ("/v1/chat/completions", "/v1/completions", "/v1/embeddings")
_MAX_CONCURRENCY = 32


def _env_int(name: str, default: int) -> int:
    import os

    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError
        return v
    except ValueError:
        raise ValueError(f"{name} must be a positive integer, got {raw!r}") from None


@dataclass
class _StoredFile:
    id: str
    filename: str
    purpose: str
    content: bytes
    created_at: int

    def meta(self) -> dict:
        return {
            "id": self.id,
            "object": "file",
            "bytes": len(self.content),
            "created_at": self.created_at,
            "filename": self.filename,
            "purpose": self.purpose,
        }


@dataclass
class _Batch:
    id: str
    endpoint: str
    input_file_id: str
    completion_window: str
    metadata: Optional[dict]
    created_at: int
    # Auth headers captured from the CREATING request: internal line
    # dispatch re-runs the full middleware chain, so an authenticated
    # deployment's auth middleware must see the creator's credentials.
    auth_headers: dict = field(default_factory=dict)
    status: str = "validating"
    output_file_id: Optional[str] = None
    error_file_id: Optional[str] = None
    errors: Optional[dict] = None
    in_progress_at: Optional[int] = None
    completed_at: Optional[int] = None
    cancelled_at: Optional[int] = None
    counts: dict = field(
        default_factory=lambda: {"total": 0, "completed": 0, "failed": 0}
    )
    _cancel: bool = False

    def as_dict(self) -> dict:
        return {
            "id": self.id,
            "object": "batch",
            "endpoint": self.endpoint,
            "errors": self.errors,
            "input_file_id": self.input_file_id,
            "completion_window": self.completion_window,
            "status": self.status,
            "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id,
            "created_at": self.created_at,
            "in_progress_at": self.in_progress_at,
            "completed_at": self.completed_at,
            "cancelled_at": self.cancelled_at,
            "request_counts": dict(self.counts),
            "metadata": self.metadata,
        }


class BatchStore:
    """In-memory files + batches + the batch runner.

    Bounded: a long-lived replica must not let the Files surface exhaust
    host memory — per-file bytes (``TPU_BATCH_MAX_FILE_BYTES``, default
    100 MB, matching the multipart zip guard) and total store bytes
    (``TPU_BATCH_STORE_BYTES``, default 1 GB) are enforced with 413s,
    and batches terminal for longer than ``TPU_BATCH_RETENTION_S``
    (default 24 h) are evicted together with their input/output files on
    the next store mutation.
    """

    def __init__(self, app) -> None:
        self._app = app
        self.files: dict[str, _StoredFile] = {}
        self.batches: dict[str, _Batch] = {}
        self.max_file_bytes = _env_int(
            "TPU_BATCH_MAX_FILE_BYTES", 100 * 1024 * 1024
        )
        self.max_store_bytes = _env_int(
            "TPU_BATCH_STORE_BYTES", 1024 * 1024 * 1024
        )
        self.retention_s = _env_int("TPU_BATCH_RETENTION_S", 24 * 3600)
        # Strong refs to runner tasks: asyncio keeps only weak ones, and
        # a GC'd runner would strand its batch in 'in_progress'.
        self._tasks: set = set()

    # -- files -----------------------------------------------------------

    def _evict_expired(self) -> None:
        """Drop batches terminal past retention, plus their files."""
        cutoff = int(time.time()) - self.retention_s
        for bid, b in list(self.batches.items()):
            done_at = b.completed_at or b.cancelled_at
            if b.status == "failed":
                done_at = done_at or b.created_at
            if done_at is None or done_at > cutoff:
                continue
            del self.batches[bid]
            for fid in (b.input_file_id, b.output_file_id, b.error_file_id):
                if fid:
                    self.files.pop(fid, None)
        # Orphan uploads (never attached to a batch, or whose batch is
        # gone) age out too, or they would accumulate forever.
        live = {
            fid
            for b in self.batches.values()
            for fid in (b.input_file_id, b.output_file_id, b.error_file_id)
            if fid
        }
        for fid, f in list(self.files.items()):
            if fid not in live and f.created_at <= cutoff:
                del self.files[fid]

    def store_bytes(self) -> int:
        return sum(len(f.content) for f in self.files.values())

    def add_file(
        self, filename: str, purpose: str, content: bytes,
        internal: bool = False,
    ) -> dict:
        self._evict_expired()
        if not internal:
            # Runner-produced output files bypass the caps: failing a
            # finished batch over quota would lose paid-for results —
            # retention eviction bounds them instead.
            if len(content) > self.max_file_bytes:
                raise ErrorPayloadTooLarge(
                    "file", len(content), self.max_file_bytes
                )
            if self.store_bytes() + len(content) > self.max_store_bytes:
                raise ErrorPayloadTooLarge(
                    "file store", self.store_bytes() + len(content),
                    self.max_store_bytes,
                )
        fid = f"file-{uuid.uuid4().hex[:24]}"
        self.files[fid] = _StoredFile(
            fid, filename, purpose, content, int(time.time())
        )
        return self.files[fid].meta()

    # -- batch execution -------------------------------------------------

    async def _dispatch_line(self, batch: _Batch, line: dict) -> tuple:
        """One JSONL request line through the app router. Returns
        (custom_id, status_code, body_dict_or_error)."""
        if not isinstance(line, dict):
            return (
                None,
                400,
                {"error": {"message": "line must be a JSON object"}},
            )
        custom_id = line.get("custom_id")
        method = (line.get("method") or "POST").upper()
        url = line.get("url")
        body = line.get("body")
        if (
            not isinstance(custom_id, str)
            or method != "POST"
            or url != batch.endpoint
            or not isinstance(body, dict)
        ):
            return (
                custom_id,
                400,
                {
                    "error": {
                        "message": (
                            "line must be {custom_id: str, method: 'POST', "
                            f"url: {batch.endpoint!r}, body: object}}"
                        )
                    }
                },
            )
        if body.get("stream"):
            return (
                custom_id,
                400,
                {"error": {"message": "stream is not supported in batches"}},
            )
        raw = RawRequest(
            method="POST",
            target=batch.endpoint,
            version="HTTP/1.1",
            headers={
                "content-type": "application/json",
                **batch.auth_headers,
            },
            body=json.dumps(body).encode(),
        )
        resp = await self._app.router(raw)
        try:
            payload = json.loads(resp.body or b"{}")
        except json.JSONDecodeError:
            payload = {"error": {"message": "non-JSON handler response"}}
        return custom_id, resp.status, payload

    async def run_batch(self, batch: _Batch) -> None:
        # Any escape from the runner must land the batch in a terminal
        # state — a stuck 'in_progress' hangs every poller.
        try:
            await self._run_batch(batch)
        except Exception as exc:  # noqa: BLE001
            batch.status = "failed"
            batch.errors = {
                "object": "list",
                "data": [{
                    "code": "runner_error",
                    "message": f"{type(exc).__name__}: {exc}",
                }],
            }

    async def _run_batch(self, batch: _Batch) -> None:
        inp = self.files[batch.input_file_id]
        lines = []
        try:
            for ln in inp.content.decode("utf-8").splitlines():
                if ln.strip():
                    lines.append(json.loads(ln))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            batch.status = "failed"
            batch.errors = {
                "object": "list",
                "data": [{
                    "code": "invalid_jsonl",
                    "message": f"input file is not valid JSONL: {exc}",
                }],
            }
            return
        batch.counts["total"] = len(lines)
        batch.status = "in_progress"
        batch.in_progress_at = int(time.time())

        sem = asyncio.Semaphore(_MAX_CONCURRENCY)
        results: list = [None] * len(lines)

        async def one(i: int, line: dict) -> None:
            async with sem:
                if batch._cancel:
                    return
                results[i] = await self._dispatch_line(batch, line)

        await asyncio.gather(*(one(i, ln) for i, ln in enumerate(lines)))

        out_lines, err_lines = [], []
        for i, res in enumerate(results):
            if res is None:  # cancelled before dispatch
                continue
            custom_id, status, payload = res
            rid = f"batch_req_{batch.id[len('batch_'):]}_{i}"
            if status == 200:
                batch.counts["completed"] += 1
                out_lines.append(json.dumps({
                    "id": rid,
                    "custom_id": custom_id,
                    "response": {
                        "status_code": status,
                        "request_id": rid,
                        "body": payload,
                    },
                    "error": None,
                }))
            else:
                batch.counts["failed"] += 1
                msg = (
                    payload.get("error", {}).get("message")
                    if isinstance(payload.get("error"), dict)
                    else str(payload)
                )
                err_lines.append(json.dumps({
                    "id": rid,
                    "custom_id": custom_id,
                    "response": {"status_code": status, "body": payload},
                    "error": {"code": str(status), "message": msg},
                }))
        if out_lines:
            batch.output_file_id = self.add_file(
                f"{batch.id}_output.jsonl", "batch_output",
                ("\n".join(out_lines) + "\n").encode(), internal=True,
            )["id"]
        if err_lines:
            batch.error_file_id = self.add_file(
                f"{batch.id}_errors.jsonl", "batch_output",
                ("\n".join(err_lines) + "\n").encode(), internal=True,
            )["id"]
        if batch._cancel:
            batch.status = "cancelled"
            batch.cancelled_at = int(time.time())
        else:
            batch.status = "completed"
            batch.completed_at = int(time.time())


def add_openai_batch_routes(app) -> BatchStore:
    """Register /v1/files + /v1/batches on a gofr_tpu App. Returns the
    store (tests and ops can reach in)."""
    store = BatchStore(app)

    @app.post("/v1/files")
    async def upload_file(ctx):  # noqa: ANN001
        bound = ctx.request.bind({})
        part = bound.get("file")
        purpose = bound.get("purpose") or ""
        if part is None or not hasattr(part, "data"):
            raise ErrorInvalidParam([
                "multipart field 'file' (the JSONL upload) is required"
            ])
        if purpose != "batch":
            raise ErrorInvalidParam(["purpose must be 'batch'"])
        return Raw(
            store.add_file(part.filename or "upload.jsonl", purpose, part.data),
            status=200,
        )

    @app.get("/v1/files/{id}")
    async def file_meta(ctx):  # noqa: ANN001
        fid = ctx.request.path_param("id")
        f = store.files.get(fid)
        if f is None:
            raise ErrorEntityNotFound("file", fid)
        return Raw(f.meta())

    @app.get("/v1/files/{id}/content")
    async def file_content(ctx):  # noqa: ANN001
        fid = ctx.request.path_param("id")
        f = store.files.get(fid)
        if f is None:
            raise ErrorEntityNotFound("file", fid)
        # octet-stream, like the upstream API: downloads are raw bytes.
        return FileResponse(f.content, content_type="application/octet-stream")

    @app.delete("/v1/files/{id}")
    async def delete_file(ctx):  # noqa: ANN001
        fid = ctx.request.path_param("id")
        if store.files.pop(fid, None) is None:
            raise ErrorEntityNotFound("file", fid)
        # 200 + body (OpenAI wire shape), not the framework DELETE→204.
        return Raw({"id": fid, "object": "file", "deleted": True}, status=200)

    @app.post("/v1/batches")
    async def create_batch(ctx):  # noqa: ANN001
        body = ctx.request.json()
        if not isinstance(body, dict):
            raise ErrorInvalidParam(["body"])
        endpoint = body.get("endpoint")
        input_file_id = body.get("input_file_id")
        if endpoint not in _ENDPOINTS:
            raise ErrorInvalidParam([
                f"endpoint must be one of {list(_ENDPOINTS)}"
            ])
        if input_file_id not in store.files:
            raise ErrorInvalidParam([
                f"input_file_id {input_file_id!r} is not an uploaded file"
            ])
        batch = _Batch(
            id=f"batch_{uuid.uuid4().hex[:24]}",
            endpoint=endpoint,
            input_file_id=input_file_id,
            completion_window=body.get("completion_window") or "24h",
            metadata=body.get("metadata"),
            created_at=int(time.time()),
            auth_headers={
                k: v
                for k, v in ctx.request.headers.items()
                if k in ("authorization", "x-api-key")
            },
        )
        store.batches[batch.id] = batch
        task = asyncio.get_running_loop().create_task(
            store.run_batch(batch)
        )
        store._tasks.add(task)
        task.add_done_callback(store._tasks.discard)
        return Raw(batch.as_dict(), status=200)

    @app.get("/v1/batches")
    async def list_batches(ctx):  # noqa: ANN001
        raw_limit = ctx.request.param("limit") or "20"
        try:
            limit = max(0, int(raw_limit))
        except ValueError:
            raise ErrorInvalidParam(["limit must be an integer"]) from None
        ordered = sorted(
            store.batches.values(), key=lambda b: (-b.created_at, b.id)
        )
        # OpenAI cursor pagination: `after` names the last id of the
        # previous page; SDK auto-pagination depends on it.
        after = ctx.request.param("after")
        start = 0
        if after:
            for i, b in enumerate(ordered):
                if b.id == after:
                    start = i + 1
                    break
            else:
                raise ErrorInvalidParam([f"unknown 'after' cursor {after!r}"])
        page = ordered[start : start + limit]
        return Raw({
            "object": "list",
            "data": [b.as_dict() for b in page],
            "first_id": page[0].id if page else None,
            "last_id": page[-1].id if page else None,
            "has_more": start + limit < len(ordered),
        })

    @app.get("/v1/batches/{id}")
    async def get_batch(ctx):  # noqa: ANN001
        bid = ctx.request.path_param("id")
        b = store.batches.get(bid)
        if b is None:
            raise ErrorEntityNotFound("batch", bid)
        return Raw(b.as_dict())

    @app.post("/v1/batches/{id}/cancel")
    async def cancel_batch(ctx):  # noqa: ANN001
        bid = ctx.request.path_param("id")
        b = store.batches.get(bid)
        if b is None:
            raise ErrorEntityNotFound("batch", bid)
        if b.status in ("validating", "in_progress"):
            b._cancel = True
            b.status = "cancelling"
        return Raw(b.as_dict(), status=200)  # OpenAI wire-compat POST

    return store
