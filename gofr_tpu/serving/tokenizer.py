"""Tokenizers for the serving engine.

Default is a self-contained byte-level tokenizer (zero-egress environment: no
downloadable vocabularies), with special tokens at the top of the byte range:
ids 0..255 = raw bytes, 256 = BOS, 257 = EOS, 258 = PAD. Any model with
vocab ≥ 259 can serve text through it. A HuggingFace tokenizer can be
swapped in via ``TPU_TOKENIZER=<path>`` when local vocab files exist.
"""

from __future__ import annotations

from typing import Protocol, Sequence


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    bos_id = 256
    eos_id = 257
    pad_id = 258
    vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", "replace")


def tokenizer_from_config(config, logger=None) -> Tokenizer:
    path = config.get_or_default("TPU_TOKENIZER", "")
    if path:
        try:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

            class _HF:
                # Explicit None checks: id 0 is a real vocab token and a
                # missing eos must disable eos-stopping, not stop on id 0.
                bos_id = tok.bos_token_id if tok.bos_token_id is not None else -1
                eos_id = tok.eos_token_id if tok.eos_token_id is not None else -1
                pad_id = (
                    tok.pad_token_id
                    if tok.pad_token_id is not None
                    else (tok.eos_token_id if tok.eos_token_id is not None else -1)
                )

                def encode(self, text: str) -> list[int]:
                    return tok.encode(text)

                def decode(self, ids) -> str:
                    return tok.decode(list(ids), skip_special_tokens=True)

                def apply_chat_template(self, messages) -> list[int]:
                    """The model's OWN chat format (HF chat_template) —
                    used by the OpenAI-compat surface when present.

                    Returns token IDS, not a string: a rendered template
                    already contains BOS/special tokens, and re-encoding
                    it through ``encode`` (add_special_tokens=True) would
                    prepend a second BOS — the classic tokenize=False
                    pitfall."""
                    return list(tok.apply_chat_template(
                        messages, tokenize=True, add_generation_prompt=True
                    ))

            return _HF()
        except Exception as exc:
            if logger is not None:
                logger.errorf(
                    "could not load tokenizer %s (%s); using byte tokenizer",
                    path,
                    exc,
                )
    return ByteTokenizer()
