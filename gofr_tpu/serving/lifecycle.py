"""Request-lifecycle primitives: deadlines and cancellation tokens.

The serving core's resilience contract (Orca/vLLM treat mid-stream
eviction as first-class; SURVEY §2.6) needs two small, thread-safe
objects that travel WITH a request from the edge (HTTP header, gRPC
deadline) through ``serving/types.py`` into the scheduler loop:

* :class:`Deadline` — an absolute expiry on an injectable monotonic
  clock. The injectable clock is what makes deadline tests
  deterministic: a test advances a fake clock instead of sleeping.
* :class:`CancelToken` — a latch the transport layer trips when the
  client disconnects (HTTP connection drop, gRPC stream cancel) so the
  scheduler retires the sequence and frees its KV blocks within one
  decode window instead of decoding for nobody.

Both are checked by the scheduler's lifecycle reap
(``scheduler._reap_lifecycle``) once per loop iteration — O(slots)
host bookkeeping, no device traffic.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Deadline:
    """An absolute expiry measured on ``clock`` (monotonic seconds).

    Use :meth:`after` for the common "N seconds from now" form. The
    clock is injectable so tests can drive expiry deterministically.
    """

    __slots__ = ("expires_at", "_clock")

    def __init__(
        self,
        expires_at: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.expires_at = expires_at
        self._clock = clock

    @classmethod
    def after(
        cls,
        seconds: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> "Deadline":
        return cls(clock() + seconds, clock)

    def remaining(self) -> float:
        """Seconds until expiry (negative once expired)."""
        return self.expires_at - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self.expires_at

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


class CancelToken:
    """A one-way latch: once cancelled, stays cancelled.

    ``threading.Event``-backed so any thread (asyncio transport
    callback, gRPC cancel handler, test) can trip it and the scheduler
    thread observes it without locking.
    """

    __slots__ = ("_evt",)

    def __init__(self) -> None:
        self._evt = threading.Event()

    def cancel(self) -> None:
        self._evt.set()

    @property
    def cancelled(self) -> bool:
        return self._evt.is_set()

    def __repr__(self) -> str:
        return f"CancelToken(cancelled={self.cancelled})"


def coalesce_deadline(
    deadline: Optional[Deadline], deadline_s: Optional[float]
) -> Optional[Deadline]:
    """An explicit Deadline wins (it may ride a test clock); otherwise a
    relative budget becomes one on the real monotonic clock."""
    if deadline is not None:
        return deadline
    if deadline_s is not None:
        return Deadline.after(float(deadline_s))
    return None
