"""Request-lifecycle primitives: deadlines and cancellation tokens.

The serving core's resilience contract (Orca/vLLM treat mid-stream
eviction as first-class; SURVEY §2.6) needs two small, thread-safe
objects that travel WITH a request from the edge (HTTP header, gRPC
deadline) through ``serving/types.py`` into the scheduler loop:

* :class:`Deadline` — an absolute expiry on an injectable monotonic
  clock. The injectable clock is what makes deadline tests
  deterministic: a test advances a fake clock instead of sleeping.
* :class:`CancelToken` — a latch the transport layer trips when the
  client disconnects (HTTP connection drop, gRPC stream cancel) so the
  scheduler retires the sequence and frees its KV blocks within one
  decode window instead of decoding for nobody.

Both are checked by the scheduler's lifecycle reap
(``scheduler._reap_lifecycle``) once per loop iteration — O(slots)
host bookkeeping, no device traffic.

:class:`AggregateThroughput` rides along: the sliding-window aggregate
tokens/sec estimate behind projected-wait load shedding (it shares this
module's injectable-clock determinism contract).
"""

from __future__ import annotations

import queue as _queue
import threading

import time
from collections import deque
from typing import Callable, Optional

from gofr_tpu.analysis import lockcheck


class Deadline:
    """An absolute expiry measured on ``clock`` (monotonic seconds).

    Use :meth:`after` for the common "N seconds from now" form. The
    clock is injectable so tests can drive expiry deterministically.
    """

    __slots__ = ("expires_at", "_clock")

    def __init__(
        self,
        expires_at: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.expires_at = expires_at
        self._clock = clock

    @classmethod
    def after(
        cls,
        seconds: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> "Deadline":
        return cls(clock() + seconds, clock)

    def remaining(self) -> float:
        """Seconds until expiry (negative once expired)."""
        return self.expires_at - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self.expires_at

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


class CancelToken:
    """A one-way latch: once cancelled, stays cancelled.

    ``threading.Event``-backed so any thread (asyncio transport
    callback, gRPC cancel handler, test) can trip it and the scheduler
    thread observes it without locking.
    """

    __slots__ = ("_evt",)

    def __init__(self) -> None:
        self._evt = threading.Event()

    def cancel(self) -> None:
        self._evt.set()

    @property
    def cancelled(self) -> bool:
        return self._evt.is_set()

    def __repr__(self) -> str:
        return f"CancelToken(cancelled={self.cancelled})"


class AggregateThroughput:
    """Sliding-window aggregate tokens/sec across the WHOLE batch.

    The projected-wait load shedder divides the queue's token backlog by
    a throughput estimate. A per-request EWMA (the previous estimator)
    measures one stream's decode rate, which under continuous batching
    underestimates the engine's aggregate by roughly the batch size —
    at 8 concurrent streams it sheds ~8× too eagerly. This estimator
    sums every emitted token across all slots over a sliding wall-clock
    window, so the rate is the engine's, not one request's.

    The scheduler thread calls :meth:`note` once per emitted token;
    consecutive notes within ``bucket_s`` coalesce into one bucket, so
    the deque holds O(window/bucket) entries regardless of token rate.
    Thread-safe (noted from the scheduler thread, read from submit
    paths); the clock is injectable for deterministic tests.
    """

    def __init__(
        self,
        window_s: float = 30.0,
        *,
        bucket_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.window_s = float(window_s)
        self._bucket_s = float(bucket_s)
        self._clock = clock
        self._lock = lockcheck.make_lock("AggregateThroughput._lock")
        # (bucket start time, tokens in bucket); _total mirrors the sum.
        self._buckets: deque[tuple[float, int]] = deque()
        self._total = 0

    def note(self, n_tokens: int = 1, now: Optional[float] = None) -> None:
        """Record ``n_tokens`` emissions at ``now`` (defaults to the
        clock)."""
        t = self._clock() if now is None else now
        with self._lock:
            if self._buckets and t - self._buckets[-1][0] < self._bucket_s:
                bt, bn = self._buckets[-1]
                self._buckets[-1] = (bt, bn + n_tokens)
            else:
                self._buckets.append((t, n_tokens))
            self._total += n_tokens
            self._prune(t)

    def rate(self, now: Optional[float] = None) -> float:
        """Aggregate tokens/sec over the window; 0.0 with no (or too
        little) signal so callers can fall back to a prior."""
        t = self._clock() if now is None else now
        with self._lock:
            self._prune(t)
            if not self._buckets:
                return 0.0
            span = t - self._buckets[0][0]
            # Below half a bucket of span the division is noise, but an
            # idle-then-burst engine must not report 0: treat the burst
            # as having taken one bucket interval.
            return self._total / max(span, self._bucket_s)

    def reset(self) -> None:
        """Forget history (engine restart: the old engine's rate says
        nothing about the fresh one's warm-up)."""
        with self._lock:
            self._buckets.clear()
            self._total = 0

    def _prune(self, now: float) -> None:
        # Callers hold self._lock.
        cutoff = now - self.window_s
        while self._buckets and self._buckets[0][0] < cutoff:
            _, n = self._buckets.popleft()
            self._total -= n


class HedgeBudget:
    """Token-bucket budget for hedged/retried requests (replica pool).

    Unbounded hedging doubles load exactly when the tier is already
    slow — the classic retry-storm amplifier. This bucket caps extra
    attempts: it starts full at ``burst`` tokens and refills at
    ``rate_per_s``; every hedge or failover retry must
    :meth:`try_acquire` a token first, and a drained bucket means the
    request simply waits on its primary attempt instead of multiplying.

    Deterministic by construction (this module's contract): the clock is
    injectable and refill is computed, never slept for. Thread-safe —
    acquired from request threads and the pool's prober alike.
    """

    def __init__(
        self,
        burst: float = 8.0,
        rate_per_s: float = 2.0,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.burst = max(0.0, float(burst))
        self.rate_per_s = max(0.0, float(rate_per_s))
        self._clock = clock
        self._lock = lockcheck.make_lock("HedgeBudget._lock")
        self._tokens = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        # Callers hold self._lock.
        if now > self._last:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate_per_s
            )
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; False (no partial take, no
        blocking) when the budget is exhausted."""
        with self._lock:
            self._refill(self._clock())
            if self._tokens < n:
                return False
            self._tokens -= n
            return True

    def available(self) -> float:
        """Current token balance (after refill) — observability only."""
        with self._lock:
            self._refill(self._clock())
            return self._tokens


#: SLO-class dequeue rank: interactive jumps standard jumps batch.
#: Unknown classes rank as standard (the same never-400 fallback the
#: brownout shedder uses).
_CLASS_RANK = {"interactive": 0, "standard": 1, "batch": 2}


class ClassPriorityQueue:
    """The admission queue with per-SLO-class priority DEQUEUE.

    PR 12 gave requests an SLO class (``X-SLO-Class``: interactive |
    standard | batch) but only used it to apportion the brownout-cut
    admission budget — the queue itself stayed strict FIFO, so one
    queued batch burst still delayed every interactive request behind
    it. This queue reorders at POP time instead:

    * pop the head of the highest-priority non-empty class — stable
      FIFO *within* a class (one deque per class, append/popleft only);
    * **starvation bound**: a lower-class head that has waited longer
      than ``promote_after_s`` is promoted — among over-age heads the
      OLDEST pops first regardless of class, so batch work is delayed
      by at most the promotion window, never forever;
    * ``promote_after_s <= 0`` disables classing entirely: a single
      FIFO deque, byte-identical to the pre-PR ``queue.Queue`` order.

    The API is the ``queue.Queue`` subset the engine/scheduler actually
    use (``put_nowait``/``get_nowait``/``qsize``/``empty``/``maxsize``),
    so it drops into ``engine._pending`` unchanged. Put happens on
    submit threads, get on the scheduler thread — one lock covers the
    deques. The clock is injectable so the ordering contract (including
    promotion) is testable with stated times.
    """

    #: Prefix-aware pop scans at most this many entries from the chosen
    #: lane's head — the tie-break stays O(1)-ish however deep the
    #: backlog gets.
    PREFIX_SCAN = 16

    def __init__(
        self,
        maxsize: int = 0,
        *,
        promote_after_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
        classify: Callable[[object], str] = (
            lambda req: str(getattr(req, "slo_class", "standard"))
        ),
        prefix_probe: Optional[Callable[[object], bool]] = None,
    ) -> None:
        self.maxsize = int(maxsize)
        self.promote_after_s = float(promote_after_s)
        self._clock = clock
        self._classify = classify
        # Hit-aware admission ordering (TPU_QUEUE_PREFIX_AWARE): within
        # the chosen class, pop a request with a known radix-prefix hit
        # ahead of its same-class peers (the probe is a host-side trie
        # walk — cheap). None (default) keeps pop order byte-identical.
        self._prefix_probe = prefix_probe
        self._lock = lockcheck.make_lock("ClassPriorityQueue._lock")
        # rank → FIFO of (enqueued_at, request). Rank 1 doubles as THE
        # queue when classing is off.
        self._lanes: dict[int, deque] = {0: deque(), 1: deque(), 2: deque()}

    def qsize(self) -> int:
        with self._lock:
            return sum(len(lane) for lane in self._lanes.values())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put_nowait(self, req: object) -> None:
        with self._lock:
            if 0 < self.maxsize <= sum(
                len(lane) for lane in self._lanes.values()
            ):
                raise _queue.Full
            rank = 1
            if self.promote_after_s > 0:
                rank = _CLASS_RANK.get(self._classify(req), 1)
            self._lanes[rank].append((self._clock(), req))

    def get_nowait(self) -> object:
        with self._lock:
            now = self._clock()
            pick: Optional[int] = None
            promoted = False
            if self.promote_after_s > 0:
                # Starvation bound first: among heads past the
                # promotion age, the oldest wins whatever its class.
                oldest: Optional[float] = None
                for rank, lane in self._lanes.items():
                    if not lane:
                        continue
                    at = lane[0][0]
                    if now - at > self.promote_after_s and (
                        oldest is None or at < oldest
                    ):
                        oldest, pick = at, rank
                promoted = pick is not None
            if pick is None:
                pick = next(
                    (r for r in (0, 1, 2) if self._lanes[r]), None
                )
            if pick is None:
                raise _queue.Empty
            lane = self._lanes[pick]
            if self._prefix_probe is not None and not promoted:
                # WITHIN the class, break the FIFO tie toward a request
                # with a known prefix hit (its prefill is mostly free).
                # Promotion picks are exempt — the starvation bound is
                # a hard ordering contract, not a tie.
                for i in range(min(len(lane), self.PREFIX_SCAN)):
                    try:
                        hit = bool(self._prefix_probe(lane[i][1]))
                    except Exception:  # noqa: BLE001 — a probe bug must not wedge dequeue
                        hit = False
                    if hit:
                        if i == 0:
                            break
                        req = lane[i][1]
                        del lane[i]
                        return req
            return lane.popleft()[1]


def coalesce_deadline(
    deadline: Optional[Deadline], deadline_s: Optional[float]
) -> Optional[Deadline]:
    """An explicit Deadline wins (it may ride a test clock); otherwise a
    relative budget becomes one on the real monotonic clock."""
    if deadline is not None:
        return deadline
    if deadline_s is not None:
        return Deadline.after(float(deadline_s))
    return None
