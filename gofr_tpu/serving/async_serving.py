"""Durable event-driven inference: the async serving plane (ISSUE 18).

``AsyncServingPlane`` closes ROADMAP direction 4: it subscribes to a
request topic on a lease-based broker (``gofr_tpu/pubsub``), admits
each message through the engine/pool facade as SLO class ``batch`` (so
the brownout ladder and the per-tenant control plane shed async work
first, exactly as the storm A/B proves), and publishes results to a
reply topic. The headline is the delivery contract:

* **at-least-once consume** — a message is acked only after its reply
  is on the reply topic; a consumer killed mid-inference simply stops
  renewing its lease and the broker redelivers;
* **bounded redelivery** — failures nack with jittered exponential
  backoff (the ``RetryConfig`` idiom: injectable rng, stated clocks);
  past ``TPU_ASYNC_REDELIVERY_MAX`` deliveries the message parks on
  the dead-letter topic with its failure and full redelivery history
  annotated — zero lost, zero silently-retried-forever;
* **exactly-once publish** — the reply publish is idempotent per
  message id AND a bounded dedup ledger records ids already replied,
  so a consumer that dies after inference but before ack cannot
  double-publish on replay;
* **graceful drain** — ``stop`` hands unfinished leases back to the
  broker (nack, budget refunded) instead of dropping them.

Wired through the whole robustness surface: ``pubsub.deliver`` /
``pubsub.publish`` / ``pubsub.ack`` fault points, the request's
``RequestTimeline`` trace id carried broker→engine→reply (traceparent
in message headers, a ``tpu.async_consume`` annotation), tenant
attribution from headers into the ledger, async metrics + the
``/debug/async`` ops read, and a consumer-lag control-plane signal
feeding ``PoolScaler`` pressure.

Off is off: ``TPU_ASYNC=0`` builds nothing — the app holds ``None``
and every hook costs one ``is not None``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Optional

from gofr_tpu import faults
from gofr_tpu.analysis import lockcheck
from gofr_tpu.pubsub.broker import InMemoryBroker, LeasedMessage, make_broker
from gofr_tpu.serving.lifecycle import CancelToken, Deadline
from gofr_tpu.serving.observability import emit_instant_span
from gofr_tpu.service.options import RetryConfig

#: Request-payload keys forwarded to the engine facade verbatim.
_GEN_KEYS = (
    "max_new_tokens", "temperature", "stop_on_eos", "stop", "top_p",
    "seed", "adapter",
)


class _Inflight:
    """One leased message riding the engine."""

    __slots__ = ("msg", "req", "cancel", "submitted_at")

    def __init__(
        self, msg: LeasedMessage, req: Any, cancel: CancelToken,
        submitted_at: float,
    ) -> None:
        self.msg = msg
        self.req = req
        self.cancel = cancel
        self.submitted_at = submitted_at


class AsyncServingPlane:
    """The pubsub→engine→reply pump (module docstring).

    Deterministically steppable: ``step()`` runs one lease/complete
    pass and is what both the background thread and the tests drive —
    the thread adds liveness, never semantics. ``kill()`` abandons all
    state without nacking (the simulated crash the at-least-once
    acceptance test uses); the broker's lease expiry is the recovery.
    """

    def __init__(
        self,
        engine: Any,
        broker: InMemoryBroker,
        *,
        request_topic: str = "tpu.requests",
        reply_topic: str = "tpu.replies",
        dlq_topic: str = "tpu.dlq",
        redelivery_max: int = 5,
        lease_s: float = 30.0,
        max_inflight: int = 4,
        deadline_s: float = 300.0,
        retry: Optional[RetryConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        poll_s: float = 0.05,
        dedup_max: int = 2048,
        tenant_queue_max: int = 0,
        metrics: Any = None,
        logger: Any = None,
        model_name: str = "",
    ) -> None:
        self.engine = engine
        self.broker = broker
        self.request_topic = request_topic
        self.reply_topic = reply_topic
        self.dlq_topic = dlq_topic
        #: Max deliveries before dead-letter: first attempt + this many
        #: redeliveries.
        self.redelivery_max = max(0, int(redelivery_max))
        self.lease_s = max(0.001, float(lease_s))
        self.max_inflight = max(1, int(max_inflight))
        self.deadline_s = max(0.0, float(deadline_s))
        self.retry = retry if retry is not None else RetryConfig(
            backoff_s=1.0, jitter=0.5, max_backoff_s=60.0
        )
        self.poll_s = max(0.001, float(poll_s))
        self.dedup_max = max(1, int(dedup_max))
        #: Per-tenant leased+ready backlog bound
        #: (``TPU_ASYNC_TENANT_QUEUE_MAX``; 0 = unbounded): one
        #: misbehaving publisher must not occupy every lease slot and
        #: starve other tenants' queues. Over-quota deliveries park a
        #: quota-annotated DLQ record immediately — redelivering them
        #: would just re-collide with the same full backlog.
        self.tenant_queue_max = max(0, int(tenant_queue_max))
        self._clock = clock
        self._metrics = metrics
        self._logger = logger
        self.model_name = model_name or str(
            getattr(engine, "model_name", "") or ""
        )
        self._sub = broker.subscribe(request_topic, lease_s=self.lease_s)
        self._lock = lockcheck.make_lock("AsyncServingPlane._lock")
        self._inflight: list[_Inflight] = []
        #: The bounded dedup ledger: message id → reply-publish stamp.
        #: Consulted BEFORE inference so a replay after a lost ack skips
        #: straight to ack — the exactly-once-publish half.
        self._ledger: dict[str, float] = {}
        self._ledger_order: list[str] = []
        #: tenant → ids of messages this consumer has seen and not yet
        #: terminally resolved (in flight, or nacked and awaiting
        #: redelivery) — the "leased+ready" backlog the quota bounds.
        #: Ids survive nacks (a redelivery is the same logical message)
        #: and leave at the terminal ack (reply published or
        #: dead-lettered).
        self._tenant_backlog: dict[str, set[str]] = {}
        self.counters: dict[str, int] = {
            "consumed": 0, "published": 0, "redelivered": 0,
            "dead_lettered": 0, "nacked": 0, "deduped": 0,
            "deliver_errors": 0, "publish_errors": 0, "ack_errors": 0,
            "quota_rejected": 0,
        }
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._draining = False
        self._thread = threading.Thread(
            target=self._run, name="async-serving", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                did = self.step()
            except Exception as exc:  # noqa: BLE001 — the pump must survive any single-message bug
                did = 0
                if self._logger is not None:
                    self._logger.errorf("async plane step failed: %s", exc)
            if did == 0:
                self._stop.wait(self.poll_s)

    def stop(self, drain_s: float = 5.0) -> None:
        """Graceful drain: stop leasing, give in-flight work up to
        ``drain_s`` wall seconds to finish (replies publish normally),
        then cancel and *nack* whatever remains — leases go back to the
        broker with their budget refunded, never dropped."""
        self._draining = True
        deadline = time.monotonic() + max(0.0, float(drain_s))
        if self._thread is not None:
            while self.inflight_count() and time.monotonic() < deadline:
                self._stop.wait(min(0.01, self.poll_s))
            self._stop.set()
            self._thread.join(timeout=10.0)
            self._thread = None
        self.step()  # final completion pass (publishes finished work)
        self._release_unfinished()

    def kill(self) -> None:
        """Simulated crash (chaos/tests): drop everything on the floor —
        no nack, no cancel, leases left to expire. The broker's lease
        clock is the recovery path this models."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            self._inflight.clear()

    def _release_unfinished(self) -> None:
        with self._lock:
            leftover = list(self._inflight)
            self._inflight.clear()
        for entry in leftover:
            entry.cancel.cancel()
            self._sub.nack(
                entry.msg.id, delay_s=0.0, note="drain", penalize=False
            )
            self._count("nacked")
        self._publish_gauges()

    # -- the pump --------------------------------------------------------

    def step(self) -> int:
        """One pass: complete finished work, then lease new work up to
        ``max_inflight``. Returns the number of messages handled (0 =
        idle pass)."""
        did = 0
        with self._lock:
            done = [e for e in self._inflight if e.req.future.done()]
            for e in done:
                self._inflight.remove(e)
        for e in done:
            self._complete(e)
            did += 1
        while not self._draining:
            with self._lock:
                if len(self._inflight) >= self.max_inflight:
                    break
            msg = self._sub.lease()
            if msg is None:
                break
            did += 1
            self._admit(msg)
        self._publish_gauges()
        return did

    def _admit(self, msg: LeasedMessage) -> None:
        if msg.attempt > 1:
            self._count("redelivered")
            self._inc_metric("app_tpu_async_redelivered_total")
        # Replay after a lost ack: the reply already went out — ack and
        # move on, never a second publish (the dedup-ledger contract).
        with self._lock:
            replayed = msg.id in self._ledger
        if replayed:
            self._count("deduped")
            self._ack(msg)
            return
        if msg.attempt > 1 + self.redelivery_max:
            # Crash-loop redeliveries (lease expiry, no nack recorded)
            # exhaust the budget exactly like nacked failures do.
            self._dead_letter(msg, "redelivery budget exhausted")
            return
        tenant = str(msg.headers.get("tenant", ""))
        if tenant and self.tenant_queue_max > 0:
            with self._lock:
                backlog = self._tenant_backlog.setdefault(tenant, set())
                over = (
                    msg.id not in backlog
                    and len(backlog) >= self.tenant_queue_max
                )
                if not over:
                    backlog.add(msg.id)
            if over:
                self._count("quota_rejected")
                self._dead_letter(
                    msg,
                    f"tenant {tenant!r} backlog quota exceeded",
                    extra={
                        "quota": {
                            "tenant": tenant,
                            "max": self.tenant_queue_max,
                        },
                    },
                )
                return
        try:
            faults.fire(
                "pubsub.deliver",
                topic=msg.topic, message_id=msg.id, attempt=msg.attempt,
            )
            payload = json.loads(msg.value)
            if not isinstance(payload, dict) or "prompt" not in payload:
                raise ValueError("request payload must be an object with a 'prompt'")
        except Exception as exc:  # noqa: BLE001 — any delivery failure takes the nack/DLQ path, never kills the pump
            self._count("deliver_errors")
            self._fail(msg, exc)
            return
        cancel = CancelToken()
        deadline_s = float(payload.get("deadline_s", self.deadline_s) or 0.0)
        deadline = (
            Deadline.after(deadline_s, clock=self._clock)
            if deadline_s > 0 else None
        )
        kwargs: dict[str, Any] = {
            k: payload[k] for k in _GEN_KEYS if k in payload
        }
        try:
            req = self.engine.submit_generate(
                payload["prompt"],
                slo_class="batch",
                tenant=str(msg.headers.get("tenant", "")),
                traceparent=msg.headers.get("traceparent"),
                deadline=deadline,
                cancel=cancel,
                **kwargs,
            )
        except Exception as exc:  # noqa: BLE001 — sheds/param errors take the nack/DLQ path, never kill the pump
            self._fail(msg, exc)
            return
        now = self._clock()
        timeline = getattr(req, "timeline", None)
        if timeline is not None:
            timeline.annotate(
                "tpu.async_consume", now,
                topic=msg.topic, message_id=msg.id, attempt=msg.attempt,
            )
            emit_instant_span(
                "tpu.async_consume", timeline.traceparent(),
                {"topic": msg.topic, "message_id": msg.id,
                 "attempt": msg.attempt},
            )
        with self._lock:
            self._inflight.append(_Inflight(msg, req, cancel, now))

    def _complete(self, entry: _Inflight) -> None:
        msg = entry.msg
        try:
            result = entry.req.future.result(timeout=0)
        except Exception as exc:  # noqa: BLE001 — deadline/cancel/engine errors take the nack/DLQ path
            self._fail(msg, exc)
            return
        timeline = getattr(entry.req, "timeline", None)
        reply_headers = {
            "message_id": msg.id,
            "tenant": str(msg.headers.get("tenant", "")),
            "traceparent": (
                timeline.traceparent() if timeline is not None
                else str(msg.headers.get("traceparent", ""))
            ),
        }
        reply = json.dumps({
            "id": msg.id,
            "text": getattr(result, "text", ""),
            "token_ids": list(getattr(result, "token_ids", []) or []),
            "finish_reason": getattr(result, "finish_reason", ""),
            "prompt_tokens": int(getattr(result, "prompt_tokens", 0)),
            "attempt": msg.attempt,
        })
        try:
            faults.fire(
                "pubsub.publish", topic=self.reply_topic, message_id=msg.id,
            )
            self.broker.publish(
                self.reply_topic, reply, reply_headers,
                message_id=f"reply-{msg.id}",
            )
        except Exception as exc:  # noqa: BLE001 — a failed reply publish is retried via redelivery
            self._count("publish_errors")
            self._fail(msg, exc)
            return
        self._ledger_put(msg.id)
        self._count("published")
        self._inc_metric("app_tpu_async_published_total")
        self._ack(msg)

    def _ack(self, msg: LeasedMessage) -> None:
        try:
            faults.fire(
                "pubsub.ack", topic=msg.topic, message_id=msg.id,
            )
            self._sub.ack(msg.id)
        except Exception:  # noqa: BLE001 — a lost ack is recovered by lease expiry + the dedup ledger
            self._count("ack_errors")
            return
        # Terminal: the message leaves its tenant's backlog. (On an ack
        # error it stays counted — the redelivery is the same logical
        # message and must not open a quota slot.)
        tenant = str(msg.headers.get("tenant", ""))
        if tenant:
            with self._lock:
                backlog = self._tenant_backlog.get(tenant)
                if backlog is not None:
                    backlog.discard(msg.id)
                    if not backlog:
                        del self._tenant_backlog[tenant]
        self._count("consumed")
        self._inc_metric("app_tpu_async_consumed_total")

    def _fail(self, msg: LeasedMessage, exc: BaseException) -> None:
        if msg.attempt >= 1 + self.redelivery_max:
            self._dead_letter(msg, f"{type(exc).__name__}: {exc}")
            return
        # Jittered exponential backoff before the redelivery (the
        # RetryConfig idiom: injectable rng decorrelates, stated clocks
        # keep tests deterministic). attempt is 1-based.
        delay = self.retry.delay_s(max(0, msg.attempt - 1))
        self._sub.nack(
            msg.id, delay_s=delay, note=f"{type(exc).__name__}: {exc}"
        )
        self._count("nacked")
        if self._logger is not None:
            self._logger.debugf(
                "async message %s nacked (attempt %d, retry in %.2fs): %s",
                msg.id, msg.attempt, delay, exc,
            )

    def _dead_letter(
        self,
        msg: LeasedMessage,
        reason: str,
        extra: Optional[dict] = None,
    ) -> None:
        record: dict[str, Any] = {
            "id": msg.id,
            "topic": msg.topic,
            "error": reason,
            "attempts": msg.attempt,
            "history": msg.history,
            "value": msg.value,
            "headers": msg.headers,
        }
        if extra:
            record.update(extra)
        annotated = json.dumps(record)
        try:
            faults.fire(
                "pubsub.publish", topic=self.dlq_topic, message_id=msg.id,
            )
            self.broker.publish(
                self.dlq_topic, annotated, dict(msg.headers),
                message_id=f"dlq-{msg.id}",
            )
        except Exception as exc:  # noqa: BLE001 — if even the DLQ publish fails, keep the message alive
            self._count("publish_errors")
            self._sub.nack(
                msg.id, delay_s=self.retry.max_backoff_s,
                note=f"dlq publish failed: {exc}", penalize=False,
            )
            return
        self._count("dead_lettered")
        self._inc_metric("app_tpu_async_dead_lettered_total")
        if self._logger is not None:
            self._logger.errorf(
                "async message %s dead-lettered after %d deliveries: %s",
                msg.id, msg.attempt, reason,
            )
        self._ack(msg)

    # -- bookkeeping -----------------------------------------------------

    def _ledger_put(self, msg_id: str) -> None:
        with self._lock:
            if msg_id in self._ledger:
                return
            self._ledger[msg_id] = self._clock()
            self._ledger_order.append(msg_id)
            while len(self._ledger_order) > self.dedup_max:
                evicted = self._ledger_order.pop(0)
                self._ledger.pop(evicted, None)

    def _count(self, name: str) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + 1

    def _inc_metric(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.increment_counter(
                name, "model", self.model_name
            )

    def _publish_gauges(self) -> None:
        if self._metrics is None:
            return
        self._metrics.set_gauge(
            "app_tpu_async_lag", float(self.lag()),
            "model", self.model_name,
        )
        self._metrics.set_gauge(
            "app_tpu_async_inflight_leases", float(self._sub.inflight()),
            "model", self.model_name,
        )

    # -- signals / introspection ----------------------------------------

    def lag(self) -> int:
        """Request-topic backlog (ready, unleased) — the control-plane
        consumer-lag signal."""
        return self.broker.depth(self.request_topic)

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def dedup_ledger(self) -> dict[str, float]:
        with self._lock:
            return dict(self._ledger)

    def report(self) -> dict[str, Any]:
        """The ``/debug/async`` read: topics, knobs, live state, the
        delivery counters, and the dedup ledger's occupancy."""
        with self._lock:
            inflight = [e.msg.id for e in self._inflight]
            counters = dict(self.counters)
            ledger_size = len(self._ledger)
            backlog_sizes = {
                t: len(ids) for t, ids in self._tenant_backlog.items()
            }
        return {
            "enabled": True,
            "model": self.model_name,
            "request_topic": self.request_topic,
            "reply_topic": self.reply_topic,
            "dlq_topic": self.dlq_topic,
            "redelivery_max": self.redelivery_max,
            "lease_s": self.lease_s,
            "max_inflight": self.max_inflight,
            "deadline_s": self.deadline_s,
            "running": self._thread is not None,
            "draining": self._draining,
            "lag": self.lag(),
            "inflight_leases": self._sub.inflight(),
            "inflight": inflight,
            "counters": counters,
            "dedup_ledger": {"size": ledger_size, "max": self.dedup_max},
            "tenant_backlog": {
                "max": self.tenant_queue_max,
                "tenants": backlog_sizes,
            },
        }


def new_async_plane_from_config(
    config: Any,
    engine: Any,
    metrics: Any = None,
    logger: Any = None,
) -> Optional[AsyncServingPlane]:
    """Container seam (the ``new_tpu_from_config`` idiom): every knob a
    ``TPU_ASYNC_*`` env key; ``TPU_ASYNC`` off (the default) builds
    nothing and the app's hooks cost one ``is not None``."""
    enabled = str(
        config.get_or_default("TPU_ASYNC", "0")
    ).strip().lower() in ("1", "true", "yes")
    if not enabled or engine is None:
        return None
    broker = make_broker(
        str(config.get_or_default("TPU_ASYNC_BROKER", "memory")),
        dir=str(config.get_or_default("TPU_ASYNC_BROKER_DIR", "")),
    )
    plane = AsyncServingPlane(
        engine,
        broker,
        request_topic=str(config.get_or_default(
            "TPU_ASYNC_REQUEST_TOPIC", "tpu.requests")),
        reply_topic=str(config.get_or_default(
            "TPU_ASYNC_REPLY_TOPIC", "tpu.replies")),
        dlq_topic=str(config.get_or_default(
            "TPU_ASYNC_DLQ_TOPIC", "tpu.dlq")),
        redelivery_max=int(config.get_or_default(
            "TPU_ASYNC_REDELIVERY_MAX", "5")),
        lease_s=float(config.get_or_default("TPU_ASYNC_LEASE_S", "30")),
        max_inflight=int(config.get_or_default(
            "TPU_ASYNC_MAX_INFLIGHT", "4")),
        deadline_s=float(config.get_or_default(
            "TPU_ASYNC_DEADLINE_S", "300")),
        poll_s=float(config.get_or_default("TPU_ASYNC_POLL_S", "0.05")),
        dedup_max=int(config.get_or_default("TPU_ASYNC_DEDUP_MAX", "2048")),
        tenant_queue_max=int(config.get_or_default(
            "TPU_ASYNC_TENANT_QUEUE_MAX", "0")),
        metrics=metrics,
        logger=logger,
    )
    # Sustained consumer lag feeds PoolScaler pressure through the
    # engine's control plane (None-guarded: pools and control-off
    # engines simply skip the signal).
    attach = getattr(engine, "attach_async_lag", None)
    if attach is not None:
        attach(
            lambda: float(plane.lag()),
            depth=float(config.get_or_default("TPU_ASYNC_LAG_DEPTH", "0")),
            sustain_s=float(config.get_or_default(
                "TPU_ASYNC_LAG_SUSTAIN_S", "0")),
        )
    return plane
