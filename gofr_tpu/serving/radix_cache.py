"""Automatic block-level prefix caching: the host-side radix index.

``PrefixPool`` (serving/prefix_cache.py) made prefix reuse possible but
opt-in and copy-based: an operator registers exact prefixes and every
hit pays an on-device pool→slot copy. With the PAGED cache every read
and write already routes through a per-slot block table, so a
fully-filled prompt block can be shared across requests by *table
aliasing* — zero bytes moved on a hit (vLLM's PagedAttention sharing,
SGLang's RadixAttention). This module is the host-side half of that:

* a radix/trie index keyed by ``(adapter slot, chain of full-block
  token contents)`` mapping each full prompt block ever retired to its
  physical pool block id;
* LRU eviction of *unreferenced* cached blocks (refcount 1 — held only
  by the index) when the allocator runs dry or the optional cap is
  exceeded, leaf-first so the chain structure stays reachable;
* purge-on-adapter-unload, same aid discipline as ``PrefixPool``:
  cached K/V is a function of the weights that prefilled it, so a
  request only ever reuses blocks prefilled under its OWN adapter and
  unloading an adapter drops its whole subtree.

Reference discipline: the index owns exactly ONE allocator reference
per node (``BlockAllocator`` in ops/kv_cache.py). ``lookup`` increfs
every matched block UNDER the index lock and returns with those
references held — taking them later would race ``purge_aid`` on the
load/unload_lora thread, which can free the block between the walk and
the incref. The caller (scheduler admission) transfers each reference
to the slot's block table, or decrefs blocks it ends up not aliasing;
``insert`` ADOPTS the caller's reference for
every newly-created node (ownership transfers from the retiring slot's
table to the index) and leaves it with the caller for chunks whose node
already existed. Eviction and purge drop the index's own reference,
returning refcount-0 blocks to the free list.

Threading: every index mutation except :meth:`purge_aid` happens on the
scheduler thread; ``purge_aid`` runs on whichever thread calls
``load_lora``/``unload_lora``, so all public methods take the lock
(same contract as ``PrefixPool``). LRU order is a monotonic tick, not
wall time — deterministic under test.

Restart interplay: the index maps token content to PHYSICAL pool
blocks, so it dies with the cache planes — the supervisor's warm
restart rebuilds both (``engine._init_llm_serving_state``) and replayed
requests re-prefill through normal admission, re-warming the index as
they retire.
"""

from __future__ import annotations


from typing import Iterator, Optional

from gofr_tpu.analysis import lockcheck
from gofr_tpu.ops.kv_cache import BlockAllocator


class _RadixNode:
    """One cached full block: ``key`` is the block's token content (the
    edge label from its parent), ``block`` the physical pool block id.
    Depth in the trie == block index in the prefix."""

    __slots__ = ("key", "block", "parent", "children", "tick")

    def __init__(
        self,
        key: Optional[tuple[int, ...]],
        block: int,
        parent: Optional["_RadixNode"],
    ) -> None:
        self.key = key
        self.block = block
        self.parent = parent
        self.children: dict[tuple[int, ...], _RadixNode] = {}
        self.tick = 0


class RadixPrefixIndex:
    """Radix index over retired full prompt blocks (see module doc)."""

    def __init__(
        self,
        block: int,
        allocator: BlockAllocator,
        max_blocks: int = 0,
    ) -> None:
        if block <= 0:
            raise ValueError("radix index needs the paged block size")
        self.block = int(block)
        self.max_blocks = max(0, int(max_blocks))  # 0 = pool-bounded only
        self._alloc = allocator
        self._lock = lockcheck.make_lock("RadixPrefixIndex._lock")
        # One root per adapter slot; roots carry no block (block -1).
        self._roots: dict[int, _RadixNode] = {}
        self._tick = 0
        self._count = 0  # cached nodes == cached blocks

    # -- introspection ----------------------------------------------------

    @property
    def n_cached_blocks(self) -> int:
        return self._count

    def __len__(self) -> int:
        return self._count

    def cached_block_ids(self) -> list[int]:
        """Every physical block the index currently holds a reference
        to (tests/invariant checks)."""
        with self._lock:
            return [n.block for n in self._iter_nodes()]

    # -- core -------------------------------------------------------------

    def _chunks(self, ids: list[int]) -> Iterator[tuple[int, ...]]:
        B = self.block
        for lo in range(0, (len(ids) // B) * B, B):
            yield tuple(ids[lo : lo + B])

    def _iter_nodes(self) -> Iterator[_RadixNode]:
        stack = [c for r in self._roots.values() for c in r.children.values()]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def lookup(self, ids: list[int], aid: int = 0) -> tuple[list[int], int]:
        """Longest cached full-block prefix of ``ids`` under adapter
        ``aid`` → (physical block ids, matched token count). Refreshes
        LRU order on the walked chain and increfs every returned block
        while still holding the index lock (``purge_aid``/``evict`` take
        the same lock, so a concurrent purge can never free a block
        between the walk and the incref). The caller owns one reference
        per returned block: it transfers each to a slot table, or
        decrefs the ones it does not alias."""
        with self._lock:
            node = self._roots.get(aid)
            if node is None:
                return [], 0
            blocks: list[int] = []
            for chunk in self._chunks(ids):
                child = node.children.get(chunk)
                if child is None:
                    break
                self._tick += 1
                child.tick = self._tick
                self._alloc.incref(child.block)
                blocks.append(child.block)
                node = child
            return blocks, len(blocks) * self.block

    def peek(self, ids: list[int], aid: int = 0) -> int:
        """Matched-token count of the longest cached full-block prefix
        — the NON-MUTATING twin of :meth:`lookup`: no increfs, no LRU
        refresh, so an admission-ordering probe
        (``TPU_QUEUE_PREFIX_AWARE``) can ask "would this hit?" without
        pinning blocks or perturbing eviction order."""
        with self._lock:
            node = self._roots.get(aid)
            if node is None:
                return 0
            matched = 0
            for chunk in self._chunks(ids):
                child = node.children.get(chunk)
                if child is None:
                    break
                matched += self.block
                node = child
            return matched

    def insert(
        self, ids: list[int], blocks: list[int], aid: int = 0
    ) -> list[bool]:
        """Index a retiring request's full prompt blocks: ``blocks[j]``
        holds the K/V of ``ids``' j-th full block. Returns one flag per
        block — True when a new node ADOPTED the caller's allocator
        reference (the caller must NOT decref it), False when a node for
        that content already existed (the caller keeps — and releases —
        its own reference; the index keeps the incumbent block, so
        duplicate-content races converge on one physical block)."""
        adopted: list[bool] = []
        with self._lock:
            node = self._roots.get(aid)
            if node is None:
                node = self._roots[aid] = _RadixNode(None, -1, None)
            for chunk, bid in zip(self._chunks(ids), blocks):
                child = node.children.get(chunk)
                if child is None:
                    child = _RadixNode(chunk, bid, node)
                    node.children[chunk] = child
                    self._count += 1
                    adopted.append(True)
                else:
                    adopted.append(False)
                self._tick += 1
                child.tick = self._tick
                node = child
            if self.max_blocks and self._count > self.max_blocks:
                self._evict_locked(self._count - self.max_blocks)
        return adopted

    # -- eviction / purge -------------------------------------------------

    def evict(self, n_blocks: int = 1) -> int:
        """Free up to ``n_blocks`` pool blocks by dropping LRU cached
        entries nobody references (allocator pressure path). Returns how
        many blocks actually returned to the free list."""
        with self._lock:
            return self._evict_locked(n_blocks)

    def _evict_locked(self, n_blocks: int) -> int:
        """Drop up to ``n_blocks`` least-recently-used evictable
        entries: LEAVES (no children — evicting an interior node would
        orphan its subtree's chain) whose block only the index
        references (refcount 1 — blocks aliased into live slot tables
        stay put). One trie scan collects every currently-evictable
        leaf oldest-first (a batched grow under pool pressure must not
        pay a full scan PER block); dropping a whole chain's leaf can
        expose its parent as newly evictable, so re-scan while the
        target is unmet and progress is being made."""
        freed = 0
        while freed < n_blocks:
            leaves = [
                n for n in self._iter_nodes()
                if not n.children and self._alloc.refcount(n.block) == 1
            ]
            if not leaves:
                break
            leaves.sort(key=lambda n: n.tick)
            for victim in leaves[: n_blocks - freed]:
                parent = victim.parent
                if parent is not None and victim.key is not None:
                    parent.children.pop(victim.key, None)
                self._count -= 1
                self._alloc.decref(victim.block)
                freed += 1
        return freed

    def purge_aid(self, aid: int) -> int:
        """Drop every entry cached under adapter slot ``aid`` (called on
        load_lora/unload_lora — the slot id may be reused by different
        weights). Blocks still aliased into live slot tables survive
        until those slots release; the rest free immediately. Returns
        the number of entries dropped."""
        with self._lock:
            root = self._roots.pop(aid, None)
            if root is None:
                return 0
            dropped = 0
            stack = list(root.children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                self._alloc.decref(node.block)
                self._count -= 1
                dropped += 1
            return dropped

    def clear(self) -> int:
        """Drop everything (all adapters). Returns entries dropped."""
        total = 0
        with self._lock:
            aids = list(self._roots)
        for aid in aids:
            total += self.purge_aid(aid)
        return total
