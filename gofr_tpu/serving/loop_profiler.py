"""Continuous scheduler-loop profiler (ISSUE 15).

The observability stack can say *what* happened to a request (PR 6
timelines), *what* the device holds (PR 10 HBM ledger + compile
tracker), and *who* consumed it (PR 11 tenants) — but nothing could say
where a scheduler *pass's* wall time goes. The loop in
``serving/scheduler.py:_scheduler_loop`` runs ~10 distinct phases per
pass (lifecycle reap, ledger tick, brownout tick, radix watermark
sweep, tier-import apply, prefill dispatch, emit flush, window
dispatch, the device-window fetch, idle waits), and "is the TPU idle
because of host bookkeeping?" had no permanent answer — only the manual
``/debug/tpu-trace`` endpoint, which requires an operator to already
know when to look. This module is that answer, always on:

* **Per-phase attribution, exact by construction.** The scheduler
  stamps ONE clock read at each phase boundary of every pass
  (window granularity — never per row; graftlint GL011's discipline,
  and GL019 is the new static twin for hidden device waits inside host
  phases). Each stamp closes the interval since the previous stamp into
  its phase; the residual between the last stamp and the next pass's
  first closes into ``other`` — so the per-phase durations of a pass
  sum to the pass's wall time *exactly* under any clock.
* **The two derived signals.** ``app_tpu_loop_utilization`` — the busy
  fraction of loop wall time over a rolling pass window (1 − idle
  share), and ``app_tpu_loop_host_overhead_ratio`` — the share of
  *busy* time spent outside the designated device-window seam
  (``_process_window``, where the loop legitimately blocks on the
  device). THE "is host bookkeeping starving the TPU" number: high
  utilization + high host ratio = the device waits on Python; every
  bench row now carries it.
* **Stall anomalies, hysteretic.** A pass exceeding ``TPU_LOOP_STALL_S``
  (absolute) or ``TPU_LOOP_STALL_FACTOR`` × the rolling p95 (relative,
  floored so micro-benches don't trip on noise) pins a loop-anomaly
  record — full phase breakdown plus the serving context at that
  instant (queue depth, occupancy, brownout level, HBM headroom) —
  into a bounded ring served on ``/debug/loop``. The detector latches:
  a stall *storm* produces one record per incident, not one per pass,
  and re-arms only after a clean pass (hysteresis in both directions).
  Optionally (``TPU_LOOP_TRACE_MS`` > 0) an anomaly auto-triggers a
  bounded ``jax.profiler`` capture through the
  :mod:`~gofr_tpu.serving.profiler_capture` singleton, cooldown-gated
  so the storm can't thrash the profiler.
* **It measures itself.** Summarization/publication work per pass is
  accumulated into ``self_overhead_s`` and reported on ``/debug/loop``
  — the profiler's cost is a number, not a hope. The bench A/B
  (``TPU_LOOP_PROFILE=0``) pins the whole layer's cost.

Off is off: ``TPU_LOOP_PROFILE=0`` builds no profiler — every scheduler
hook degrades to one ``is not None`` and the loop is byte-identical to
the pre-profiler scheduler.

Determinism: every mutation takes the timestamp as an argument (the
caller reads the clock once per boundary), so tests drive exact phase
math, stall hysteresis, and ring bounds with stated clocks.
"""

from __future__ import annotations


import time
from collections import deque
from itertools import islice
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck

#: The bounded phase vocabulary (it appears in metric labels — GL016
#: discipline): the scheduler loop's boundaries, in pass order, plus
#: ``other`` for the residual between the last stamp and the pass end
#: (loop overhead, watchdog pet, fault seams).
PHASES = (
    "reap",           # lifecycle reap (cancel/deadline retirement)
    "ledger",         # tenant-ledger occupancy tick
    "brownout",       # brownout-controller evaluation
    "control",        # control-plane pass (signal sampling + loops)
    "sweep",          # radix-eviction watermark sweep
    "tier_import",    # disaggregated-tier payload apply
    "prefill",        # admission + chunked-prefill dispatch
    "emit_flush",     # prefill first-token emit flush
    "dispatch",       # decode-window dispatch (host-side enqueue)
    "device_window",  # window processing incl. the device fetch wait
    "idle",           # verifiably-idle wait for work
    "other",          # residual: loop overhead between stamps
)

#: The designated device-wait seam: the only phase whose time counts as
#: "the device is working / being waited on". Everything else busy is
#: host overhead. (graftlint GL019 statically pins that no OTHER phase
#: hides a device sync.)
DEVICE_PHASES = frozenset(("device_window",))

#: Phases that are waiting for work, not doing it.
IDLE_PHASES = frozenset(("idle",))

#: Relative (k × p95) stall detection floor: rolling p95s on an idle
#: CPU loop sit in the tens of microseconds, where a page fault would
#: "stall" by any multiplier. Below this absolute floor a pass is never
#: a relative anomaly.
REL_STALL_FLOOR_S = 0.05

#: Minimum rolling samples before the relative detector arms — a p95
#: over three passes is noise, not a baseline.
REL_STALL_MIN_SAMPLES = 16


def _pctl(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (0.0 empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(
        len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1)))
    )
    return sorted_vals[idx]


class LoopProfiler:
    """Per-phase time attribution + stall detection for one engine's
    scheduler loop. Written by the scheduler thread only (``begin_pass``
    / ``lap``); ``snapshot``/``describe`` read under a lock from ops
    threads. See the module docstring."""

    def __init__(
        self,
        model_name: str,
        *,
        stall_s: float = 1.0,
        stall_factor: float = 10.0,
        window: int = 256,
        anomaly_records: int = 64,
        trace_ms: int = 0,
        capture: Any = None,
        metrics: Any = None,
        logger: Any = None,
        perf: Callable[[], float] = time.perf_counter,
    ) -> None:
        self.model_name = model_name
        #: Absolute stall bound (seconds; 0 disables the absolute arm).
        self.stall_s = max(0.0, float(stall_s))
        #: Relative stall bound: k × the rolling p95 of pass wall times
        #: (0 disables the relative arm).
        self.stall_factor = max(0.0, float(stall_factor))
        self.trace_ms = max(0, int(trace_ms))
        self._capture = capture
        self._metrics = metrics
        self._logger = logger
        self._perf = perf
        #: Serving-context callback for anomaly records (queue depth,
        #: occupancy, brownout level, HBM headroom) — installed by the
        #: engine, invoked on the scheduler thread at the stall instant.
        self.context: Optional[Callable[[], dict[str, Any]]] = None
        #: Compile-counter callback (the PR 10 tracker's ``total``):
        #: a pass during which XLA compiled is attributed by the
        #: compile tracker (warm-up compiles are expected; steady-state
        #: recompiles already warn and count) — it must not ALSO pin a
        #: loop-stall anomaly, or every boot would open with one.
        self.compiles: Optional[Callable[[], int]] = None
        self._last_compiles = 0
        self._lock = lockcheck.make_lock("LoopProfiler._lock")
        # Current-pass accumulation (scheduler thread only — no lock).
        self._pass_start: Optional[float] = None
        self._last_stamp = 0.0
        self._acc: dict[str, float] = {}
        # Rolling state (under the lock).
        window = max(8, int(window))
        self.passes = 0
        self.stalls = 0
        self.self_overhead_s = 0.0
        self._phase_count: dict[str, int] = {p: 0 for p in PHASES}
        self._phase_total: dict[str, float] = {p: 0.0 for p in PHASES}
        self._phase_last: dict[str, float] = {p: 0.0 for p in PHASES}
        self._phase_window: dict[str, deque[float]] = {
            p: deque(maxlen=window) for p in PHASES
        }
        #: Rolling (total, idle, device) per pass — the utilization /
        #: host-overhead window and the relative detector's baseline.
        self._pass_window: deque[tuple[float, float, float]] = deque(
            maxlen=window
        )
        # Running window sums, maintained on append/evict so the
        # per-pass utilization/host-ratio reads are O(1) instead of
        # re-summing the window inside the lock on the hot loop; they
        # re-sync exactly from the deque once per window's worth of
        # passes to bound float drift.
        self._sum_total = 0.0
        self._sum_idle = 0.0
        self._sum_device = 0.0
        self._since_resync = 0
        # Anomaly rings: absolute-threshold stalls PIN (they survive a
        # burst of relative anomalies); relative ones ride the rolling
        # ring. Both bounded.
        anomaly_records = max(1, int(anomaly_records))
        self._anomalies: deque[dict[str, Any]] = deque(
            maxlen=anomaly_records
        )
        self._pinned: deque[dict[str, Any]] = deque(
            maxlen=max(1, anomaly_records // 4)
        )
        # Stall hysteresis latch: an incident records ONE anomaly; the
        # detector re-arms only after a pass below both thresholds, so
        # a storm of consecutive stalled passes cannot flood the ring
        # (the window/latch pair is this detector's hysteresis).
        self._stall_latched = False

    # -- scheduler-thread stamps (timestamps passed in) -----------------

    def begin_pass(self, now: float) -> None:
        """Start a pass — and close the previous one (its residual
        since the last stamp lands in ``other``, so per-phase durations
        sum to pass wall time exactly)."""
        if self._pass_start is not None:
            self._close_pass(now)
        self._pass_start = now
        self._last_stamp = now
        self._acc = {}

    def lap(self, phase: str, now: float) -> None:
        """Attribute the interval since the previous stamp to
        ``phase``. One clock read per boundary, shared — never per row."""
        if self._pass_start is None:
            return
        self._acc[phase] = self._acc.get(phase, 0.0) + max(
            0.0, now - self._last_stamp
        )
        self._last_stamp = now

    # -- pass summarization --------------------------------------------

    def _close_pass(self, now: float) -> None:
        o0 = self._perf()
        start = self._pass_start
        assert start is not None
        total = max(0.0, now - start)
        residual = max(0.0, now - self._last_stamp)
        acc = self._acc
        if residual > 0.0:
            acc["other"] = acc.get("other", 0.0) + residual
        idle = acc.get("idle", 0.0)
        device = sum(acc.get(p, 0.0) for p in DEVICE_PHASES)
        anomaly: Optional[dict[str, Any]] = None
        kind = ""
        threshold = 0.0
        with self._lock:
            self.passes += 1
            for p in PHASES:
                v = acc.get(p)
                if v is None:
                    self._phase_last[p] = 0.0
                    continue
                self._phase_count[p] += 1
                self._phase_total[p] += v
                self._phase_last[p] = v
                self._phase_window[p].append(v)
            # Maintain the running window sums across the append (and
            # the eviction it causes once the deque is full) — O(1).
            if len(self._pass_window) == self._pass_window.maxlen:
                ot, oi, od = self._pass_window[0]
                self._sum_total -= ot
                self._sum_idle -= oi
                self._sum_device -= od
            self._pass_window.append((total, idle, device))
            self._sum_total += total
            self._sum_idle += idle
            self._sum_device += device
            self._since_resync += 1
            if self._since_resync >= (self._pass_window.maxlen or 1):
                # Exact re-sync once per window of passes: amortized
                # O(1), bounds subtract-drift on the running sums.
                self._since_resync = 0
                self._sum_total = sum(t for t, _, _ in self._pass_window)
                self._sum_idle = sum(i for _, i, _ in self._pass_window)
                self._sum_device = sum(
                    d for _, _, d in self._pass_window
                )
            compiled = False
            if self.compiles is not None:
                n = int(self.compiles())
                compiled = n != self._last_compiles
                self._last_compiles = n
            if compiled:
                # XLA compiled during this pass: the time is the compile
                # tracker's to attribute (app_tpu_compile_seconds, the
                # steady-state recompile counter) — never a loop stall.
                pass
            elif self.stall_s > 0.0 and total >= self.stall_s:
                kind, threshold = "absolute", self.stall_s
            elif (
                self.stall_factor > 0.0
                and total >= REL_STALL_FLOOR_S
                and len(self._pass_window) - 1 >= REL_STALL_MIN_SAMPLES
            ):
                # The sort is the expensive part — it only runs for
                # passes already over the relative floor (no sub-floor
                # pass can be a relative stall), so sub-ms steady-state
                # passes never pay it. Baseline excludes this pass (the
                # deque's LAST entry): a stall is judged against the
                # passes that preceded it.
                baseline = sorted(
                    t for t, _, _ in islice(
                        self._pass_window, len(self._pass_window) - 1
                    )
                )
                rel = max(
                    self.stall_factor * _pctl(baseline, 0.95),
                    REL_STALL_FLOOR_S,
                )
                if total >= rel:
                    kind, threshold = "p95", rel
            if kind and not self._stall_latched:
                # New incident: latch (one record per incident — a
                # storm of stalled passes re-arms only after a clean
                # pass, the hysteresis window in the other direction).
                self._stall_latched = True
                self.stalls += 1
                anomaly = {
                    "pass": self.passes,
                    "kind": kind,
                    "total_s": round(total, 6),
                    "threshold_s": round(threshold, 6),
                    "phases": {
                        p: round(acc[p], 6) for p in PHASES if p in acc
                    },
                }
            elif not kind:
                self._stall_latched = False
            util = self._utilization_locked()
            host = self._host_overhead_locked()
        if anomaly is not None:
            self._record_anomaly(anomaly)
        if self._metrics is not None:
            self._publish(acc, util, host)
        self.self_overhead_s += max(0.0, self._perf() - o0)

    def _record_anomaly(self, anomaly: dict[str, Any]) -> None:
        """Pin the record (context snapshot + optional device-trace
        trigger run outside the stats lock — the context callback reads
        engine state and the capture takes its own locks)."""
        if self.context is not None:
            try:
                anomaly["context"] = self.context()
            except Exception:  # noqa: BLE001  # graftlint: disable=GL006 — diagnostic enrichment; the record must land even when a context read races shutdown
                pass
        captured = False
        if self._capture is not None and self.trace_ms > 0:
            captured = bool(self._capture.trigger(
                self.trace_ms, reason=f"loop-stall:{anomaly['kind']}"
            ))
        anomaly["trace_captured"] = captured
        with self._lock:
            if anomaly["kind"] == "absolute":
                self._pinned.append(anomaly)
            else:
                self._anomalies.append(anomaly)
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_loop_stalls_total",
                "model", self.model_name, "kind", anomaly["kind"],
            )
        if self._logger is not None:
            self._logger.warnf(
                "scheduler-loop stall (%s): pass %d took %.3fs "
                "(threshold %.3fs); phases=%s trace_captured=%s",
                anomaly["kind"], anomaly["pass"], anomaly["total_s"],
                anomaly["threshold_s"], anomaly["phases"], captured,
            )

    def _publish(
        self, acc: dict[str, float], util: float, host: float
    ) -> None:
        """Refresh the loop gauges from the just-closed pass. Every
        phase publishes (0.0 when absent) so the exported set always
        sums to the pass wall time."""
        m = self._metrics
        for p in PHASES:
            m.set_gauge(
                "app_tpu_loop_phase_seconds", acc.get(p, 0.0),
                "model", self.model_name, "phase", p,
            )
        m.set_gauge(
            "app_tpu_loop_utilization", util, "model", self.model_name
        )
        m.set_gauge(
            "app_tpu_loop_host_overhead_ratio", host,
            "model", self.model_name,
        )

    # -- derived signals ------------------------------------------------

    def _utilization_locked(self) -> float:
        if self._sum_total <= 0.0:
            return 0.0
        return max(
            0.0, min(1.0, 1.0 - self._sum_idle / self._sum_total)
        )

    def _host_overhead_locked(self) -> float:
        busy = self._sum_total - self._sum_idle
        if busy <= 0.0:
            return 0.0
        return max(
            0.0, min(1.0, (busy - self._sum_device) / busy)
        )

    def utilization(self) -> float:
        """Busy fraction of loop wall time over the rolling window."""
        with self._lock:
            return self._utilization_locked()

    def host_overhead_ratio(self) -> float:
        """Share of busy time outside the device-window seam — THE
        "is host bookkeeping starving the TPU" signal."""
        with self._lock:
            return self._host_overhead_locked()

    def phase_p50_ms(self) -> dict[str, float]:
        """Rolling per-phase p50 in ms (present phases only) — the
        bench JSON field."""
        with self._lock:
            out: dict[str, float] = {}
            for p in PHASES:
                win = self._phase_window[p]
                if win:
                    out[p] = round(_pctl(sorted(win), 0.50) * 1e3, 4)
            return out

    # -- rendering -----------------------------------------------------

    def describe(self) -> dict[str, Any]:
        """The compact advertisement (health details, capacity_report,
        the flight-record headline — the headroom idiom)."""
        with self._lock:
            return {
                "passes": self.passes,
                "stalls": self.stalls,
                "utilization": round(self._utilization_locked(), 6),
                "host_overhead_ratio": round(
                    self._host_overhead_locked(), 6
                ),
            }

    def snapshot(self) -> dict[str, Any]:
        """The full ``/debug/loop`` form: per-phase rolling stats,
        derived signals, stall thresholds, anomaly rings, the
        profiler's own measured overhead, and the capture singleton's
        state when auto-trace is armed."""
        with self._lock:
            phases: dict[str, Any] = {}
            for p in PHASES:
                if not self._phase_count[p]:
                    continue
                win = sorted(self._phase_window[p])
                phases[p] = {
                    "count": self._phase_count[p],
                    "total_s": round(self._phase_total[p], 6),
                    "last_s": round(self._phase_last[p], 6),
                    "p50_ms": round(_pctl(win, 0.50) * 1e3, 4),
                    "p95_ms": round(_pctl(win, 0.95) * 1e3, 4),
                }
            out: dict[str, Any] = {
                "enabled": True,
                "passes": self.passes,
                "stalls": self.stalls,
                "utilization": round(self._utilization_locked(), 6),
                "host_overhead_ratio": round(
                    self._host_overhead_locked(), 6
                ),
                "stall_s": self.stall_s,
                "stall_factor": self.stall_factor,
                "window": len(self._pass_window),
                "self_overhead_s": round(self.self_overhead_s, 6),
                "phases": phases,
                "anomalies": list(self._anomalies),
                "pinned_anomalies": list(self._pinned),
            }
        if self._capture is not None and self.trace_ms > 0:
            out["trace"] = dict(self._capture.snapshot())
            out["trace_ms"] = self.trace_ms
        return out
