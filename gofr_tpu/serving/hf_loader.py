"""HuggingFace/safetensors checkpoint ingestion (VERDICT r1 #5: real
weights, not random init, must be servable).

Maps an HF-layout Llama checkpoint (``config.json`` + ``*.safetensors``)
onto this framework's stacked-layer param pytree:

* HF linear weights are ``[out, in]``; ours contract the second-to-last
  axis, so every projection transposes to ``[in, out]``;
* per-layer tensors stack along a leading layer axis (the ``lax.scan``
  layout, ``models/transformer.py:init_transformer``);
* RoPE needs no permutation: both sides use the half-split rotate-half
  convention (``ops/rotary.py``);
* ``tie_word_embeddings`` resolves ``lm_head`` to the embedding transpose.

Memory discipline (an 8B bf16 tree must never fully materialize,
VERDICT r1 #4): tensors are read lazily per leaf via ``safe_open`` onto
the CPU backend, stacked there, then transferred — optionally quantizing
to int8 ON DEVICE leaf by leaf, so peak HBM is the int8 tree plus one
bf16 leaf.

Wired into the ``TPU_CHECKPOINT`` boot seam next to the orbax path
(``serving/checkpoint.py``): a directory with ``config.json`` /
``*.safetensors`` takes this loader; anything else takes orbax.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any


def is_hf_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and (
        os.path.exists(os.path.join(path, "config.json"))
        or bool(glob.glob(os.path.join(path, "*.safetensors")))
    )


def config_from_hf(path: str):
    """Build a TransformerConfig from an HF Llama ``config.json``."""
    import jax.numpy as jnp

    from gofr_tpu.models.transformer import TransformerConfig

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    mt = hf.get("model_type", "llama")
    if mt not in ("llama", "mistral", "mixtral", "qwen2", "gemma",
                  "gpt_neox", "gpt2"):
        raise ValueError(
            f"unsupported HF model_type {mt!r} "
            "(llama-family + qwen2 + gemma + gpt_neox + gpt2 only)"
        )
    if mt == "gpt2":
        # GPT-2: learned absolute positions, LayerNorm+bias, sequential
        # residual, gelu MLP, biases everywhere.
        g2act = {
            "gelu_new": "gelu",
            "gelu_pytorch_tanh": "gelu",
            "gelu_fast": "gelu",
            "gelu": "gelu_exact",
        }.get(hf.get("activation_function", "gelu_new"))
        if g2act is None:
            raise ValueError(
                "unsupported gpt2 activation_function "
                f"{hf.get('activation_function')!r}"
            )
        if hf.get("scale_attn_by_inverse_layer_idx"):
            raise ValueError(
                "gpt2 scale_attn_by_inverse_layer_idx is not supported"
            )
        if hf.get("scale_attn_weights") is False:
            raise ValueError(
                "gpt2 scale_attn_weights=false is not supported (attention "
                "always applies the 1/sqrt(head_dim) scale)"
            )
        return TransformerConfig(
            vocab_size=hf["vocab_size"],
            d_model=hf["n_embd"],
            n_layers=hf["n_layer"],
            n_heads=hf["n_head"],
            n_kv_heads=hf["n_head"],
            d_ff=hf.get("n_inner") or 4 * hf["n_embd"],
            max_len=hf.get("n_positions", 1024),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            dtype=jnp.bfloat16,
            attn_bias=True,
            proj_bias=True,
            norm="ln",
            ffn="mlp",
            act=g2act,
            pos_emb="learned",
        )
    if mt == "gpt_neox":
        # GPT-NeoX/Pythia: LayerNorm + parallel residual + partial
        # rotary + non-gated gelu MLP + biases everywhere; MHA.
        hidden_act = hf.get("hidden_act", "gelu")
        act = {
            # erf gelu vs the tanh approximation the weights trained on.
            "gelu": "gelu_exact",
            "gelu_fast": "gelu",
            "gelu_new": "gelu",
            "gelu_pytorch_tanh": "gelu",
        }.get(hidden_act)
        if act is None:
            raise ValueError(
                f"unsupported gpt_neox hidden_act {hidden_act!r}"
            )
        return TransformerConfig(
            vocab_size=hf["vocab_size"],
            d_model=hf["hidden_size"],
            n_layers=hf["num_hidden_layers"],
            n_heads=hf["num_attention_heads"],
            n_kv_heads=hf["num_attention_heads"],
            d_ff=hf["intermediate_size"],
            max_len=hf.get("max_position_embeddings", 2048),
            rope_theta=float(
                hf.get("rope_theta", hf.get("rotary_emb_base", 10000.0))
            ),
            norm_eps=float(hf.get("layer_norm_eps", 1e-5)),
            dtype=jnp.bfloat16,
            attn_bias=True,
            proj_bias=True,
            norm="ln",
            parallel_residual=bool(hf.get("use_parallel_residual", True)),
            rotary_pct=float(hf.get("rotary_pct", 0.25)),
            ffn="mlp",
            act=act,
        )
    return TransformerConfig(
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        d_ff=hf["intermediate_size"],
        max_len=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        dtype=jnp.bfloat16,
        # Mixtral MoE: top-k routing over stacked experts.
        n_experts=int(hf.get("num_local_experts", 0)) if mt == "mixtral" else 0,
        # Qwen2 ships QKV projection biases (its config.json has no
        # attention_bias flag in older revisions — the model_type implies it).
        attn_bias=(mt == "qwen2") or bool(hf.get("attention_bias", False)),
        n_experts_active=int(hf.get("num_experts_per_tok", 2)),
        # Mistral sliding-window attention (null/absent → full causal;
        # mixtral configs carry the field too).
        sliding_window=int(hf.get("sliding_window") or 0)
        if mt in ("mistral", "mixtral") else 0,
        # Gemma: explicit head_dim (7B: 256 ≠ 3072/16), GeGLU FFN,
        # (1+w) RMSNorm, sqrt(d_model)-scaled embeddings, tied lm_head
        # (resolved below from the embedding transpose).
        head_dim_override=int(hf.get("head_dim", 0)) if mt == "gemma" else 0,
        act="gelu" if mt == "gemma" else "silu",
        norm_offset=(mt == "gemma"),
        embed_scale=(mt == "gemma"),
    )


class _TensorSource:
    """Lazy name→tensor access over every safetensors shard, on CPU."""

    def __init__(self, path: str) -> None:
        from safetensors import safe_open

        self._by_name: dict[str, Any] = {}
        files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
        if not files:
            raise FileNotFoundError(f"no *.safetensors under {path}")
        for fname in files:
            handle = safe_open(fname, framework="flax")
            for name in handle.keys():
                self._by_name[name] = handle

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def get(self, name: str):
        import jax

        if name not in self._by_name:
            raise KeyError(f"checkpoint tensor {name!r} not found")
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return self._by_name[name].get_tensor(name)


def load_hf_llama(
    path: str,
    cfg=None,
    *,
    quant: str = "",
    mesh=None,
    logger=None,
) -> dict:
    """Load an HF Llama checkpoint into this framework's param pytree.

    cfg: expected TransformerConfig (validated against ``config.json``;
    defaults to :func:`config_from_hf`). quant: "" or "int8" — int8
    quantizes each matmul leaf on device as it lands. mesh: a
    ``jax.sharding.Mesh``; each leaf is ``device_put`` with the
    NamedSharding from its Megatron partition spec as it lands (never
    gathered on one chip — an 8B bf16 leaf set must stream straight onto
    the tp mesh, VERDICT r2 next #2), and int8 scale vectors shard with
    their output-channel axis.
    Returns the params dict ready for the serving engine.
    """
    import jax
    import jax.numpy as jnp

    from gofr_tpu.ops.quant import (
        q4_spec,
        q8_spec,
        quantize_array,
        quantize_array4,
    )

    qfn = quantize_array4 if quant == "int4" else quantize_array
    qspec = q4_spec if quant == "int4" else q8_spec

    file_cfg = (
        config_from_hf(path)
        if os.path.exists(os.path.join(path, "config.json"))
        else None
    )
    if cfg is None:
        cfg = file_cfg
    if cfg is None:
        raise ValueError(f"{path} has no config.json and no cfg was given")
    if file_cfg is not None:
        for field in ("vocab_size", "d_model", "n_layers", "n_heads",
                      "n_kv_heads", "d_ff", "n_experts",
                      "n_experts_active", "attn_bias", "head_dim_override",
                      "act", "norm_offset", "embed_scale", "norm",
                      "parallel_residual", "rotary_pct", "ffn",
                      "proj_bias", "pos_emb"):
            want, have = getattr(cfg, field), getattr(file_cfg, field)
            if want != have:
                raise ValueError(
                    f"checkpoint/config mismatch: {field}={have} in "
                    f"{path}/config.json but engine expects {want}"
                )
        if cfg.sliding_window != file_cfg.sliding_window:
            # v0.2/v0.3 Mistral checkpoints carry sliding_window: null;
            # a hard mismatch error would reject them against the v0.1
            # registry entry. Serving proceeds with the ENGINE's window
            # (a masking choice, not a weight-layout difference) — warn
            # so an unintended mismatch is visible.
            if logger is not None:
                logger.warnf(
                    "sliding_window mismatch: checkpoint %s declares %d, "
                    "engine serves with %d (masking follows the engine "
                    "config)", path, file_cfg.sliding_window,
                    cfg.sliding_window,
                )
        if (
            file_cfg.pos_emb == "learned"
            and cfg.max_len > file_cfg.max_len
        ):
            # The position table IS the context limit for learned-pos
            # models; _embed's clip would otherwise silently reuse the
            # last row past it.
            raise ValueError(
                f"max_len={cfg.max_len} exceeds the checkpoint's learned "
                f"position table ({file_cfg.max_len} rows)"
            )
    if quant and quant not in ("int8", "int4"):
        raise ValueError(f"unsupported quant {quant!r}")

    src = _TensorSource(path)
    dtype = cfg.dtype

    specs = None
    if mesh is not None:
        from gofr_tpu.models.transformer import transformer_param_specs
        from gofr_tpu.parallel.sharding import named_shardings, prune_specs

        specs = prune_specs(transformer_param_specs(cfg), mesh)

    def to_device(x, quantize: bool, spec=None):
        x = jnp.asarray(x, dtype=dtype)
        if mesh is not None:
            if quantize and quant:
                # The placed bf16 leaf is DONATED to the quantizer and
                # never read again (graftlint GL007 scopes it to this
                # branch).
                placed = jax.device_put(x, named_shardings(spec, mesh))
                return jax.jit(
                    qfn, donate_argnums=(0,),
                    out_shardings=named_shardings(qspec(spec), mesh),
                )(placed)
            return jax.device_put(x, named_shardings(spec, mesh))
        if quantize and quant:
            return jax.jit(qfn, donate_argnums=(0,))(jax.device_put(x))
        return jax.device_put(x)

    def stacked(key: str, fmt: str, transpose: bool, quantize: bool = True):
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            leaves = [src.get(fmt.format(i)) for i in range(cfg.n_layers)]
            a = jnp.stack(leaves)
            if transpose:
                a = jnp.swapaxes(a, -1, -2)  # HF [out,in] → ours [in,out]
        out = to_device(
            a, quantize, specs["layers"][key] if specs is not None else None
        )
        if logger is not None:
            logger.debugf("loaded %s x%d", fmt, cfg.n_layers)
        return out

    def stacked_experts(key: str, fmt: str):
        """Mixtral expert weights: fmt has {i}=layer, {e}=expert; HF
        stores [out, in] per expert → ours [L, E, in, out]."""
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            a = jnp.stack([
                jnp.stack([
                    jnp.swapaxes(src.get(fmt.format(i=i, e=e)), -1, -2)
                    for e in range(cfg.n_experts)
                ])
                for i in range(cfg.n_layers)
            ])  # [L, E, in, out]
        out = to_device(
            a, True, specs["layers"][key] if specs is not None else None
        )
        if logger is not None:
            logger.debugf("loaded %s x%dx%d", fmt, cfg.n_layers, cfg.n_experts)
        return out

    if "wte.weight" in src or "transformer.wte.weight" in src:
        # GPT-2 layout. Conv1D stores weights [in, out] — ALREADY our
        # contraction convention, so no transpose anywhere; c_attn packs
        # q,k,v contiguously along the output axis.
        D = cfg.d_model
        gpre = "transformer." if "transformer.wte.weight" in src else ""
        lpre = gpre + "h.{}."
        cpu = jax.devices("cpu")[0]
        qw: dict[str, list] = {"wq": [], "wk": [], "wv": []}
        qb: dict[str, list] = {"wq_b": [], "wk_b": [], "wv_b": []}
        with jax.default_device(cpu):
            for i in range(cfg.n_layers):
                w = src.get(lpre.format(i) + "attn.c_attn.weight")  # [D, 3D]
                b = src.get(lpre.format(i) + "attn.c_attn.bias")  # [3D]
                for j, t in enumerate(("wq", "wk", "wv")):
                    qw[t].append(w[:, j * D : (j + 1) * D])
                    qb[t + "_b"].append(b[j * D : (j + 1) * D])
            qw_st = {t: jnp.stack(v) for t, v in qw.items()}
            qb_st = {t: jnp.stack(v) for t, v in qb.items()}
        layers = {
            t: to_device(
                a, True, specs["layers"][t] if specs is not None else None
            )
            for t, a in qw_st.items()
        }
        layers.update({
            t: to_device(
                a, False,
                specs["layers"][t] if specs is not None else None,
            )
            for t, a in qb_st.items()
        })
        layers.update(
            wo=stacked("wo", lpre + "attn.c_proj.weight", False),
            wo_b=stacked("wo_b", lpre + "attn.c_proj.bias", False, False),
            w_up=stacked("w_up", lpre + "mlp.c_fc.weight", False),
            w_up_b=stacked("w_up_b", lpre + "mlp.c_fc.bias", False, False),
            w_down=stacked("w_down", lpre + "mlp.c_proj.weight", False),
            w_down_b=stacked(
                "w_down_b", lpre + "mlp.c_proj.bias", False, False
            ),
            attn_norm=stacked(
                "attn_norm", lpre + "ln_1.weight", False, False
            ),
            attn_norm_b=stacked(
                "attn_norm_b", lpre + "ln_1.bias", False, False
            ),
            mlp_norm=stacked("mlp_norm", lpre + "ln_2.weight", False, False),
            mlp_norm_b=stacked(
                "mlp_norm_b", lpre + "ln_2.bias", False, False
            ),
        )
        sp = specs if specs is not None else {}
        with jax.default_device(cpu):
            # Tied by default; honor an untied fine-tune's own head.
            head_name = (
                "lm_head.weight" if "lm_head.weight" in src
                else gpre + "wte.weight"
            )
            head = jnp.swapaxes(src.get(head_name), -1, -2)
        params = {
            "embed": to_device(
                src.get(gpre + "wte.weight"), False, sp.get("embed")
            ),
            "pos_embed": to_device(
                src.get(gpre + "wpe.weight"), False, sp.get("pos_embed")
            ),
            "layers": layers,
            "final_norm": to_device(
                src.get(gpre + "ln_f.weight"), False, sp.get("final_norm")
            ),
            "final_norm_b": to_device(
                src.get(gpre + "ln_f.bias"), False, sp.get("final_norm_b")
            ),
            "lm_head": to_device(head, True, sp.get("lm_head")),
        }
        if logger is not None:
            logger.infof(
                "loaded HF gpt2 checkpoint from %s (%d layers%s)",
                path, cfg.n_layers, f", {quant}" if quant else "",
            )
        return params

    if "gpt_neox.embed_in.weight" in src:
        # GPT-NeoX/Pythia layout: fused QKV [3*D, D] whose output rows
        # reshape to (heads, 3, head_dim) — split into our separate
        # q/k/v leaves — plus LayerNorm weight+bias pairs and dense
        # biases on every projection.
        H, hd, D = cfg.n_heads, cfg.head_dim, cfg.d_model
        npre = "gpt_neox.layers.{}."
        cpu = jax.devices("cpu")[0]
        qkv_w: dict[str, list] = {"wq": [], "wk": [], "wv": []}
        qkv_b: dict[str, list] = {"wq_b": [], "wk_b": [], "wv_b": []}
        with jax.default_device(cpu):
            for i in range(cfg.n_layers):
                w = src.get(
                    npre.format(i) + "attention.query_key_value.weight"
                ).reshape(H, 3, hd, D)
                b = src.get(
                    npre.format(i) + "attention.query_key_value.bias"
                ).reshape(H, 3, hd)
                for j, t in enumerate(("wq", "wk", "wv")):
                    qkv_w[t].append(
                        jnp.swapaxes(w[:, j].reshape(H * hd, D), 0, 1)
                    )
                    qkv_b[t + "_b"].append(b[:, j].reshape(H * hd))
            qkv_stacked = {
                t: jnp.stack(leaves) for t, leaves in qkv_w.items()
            }
            qkvb_stacked = {
                t: jnp.stack(leaves) for t, leaves in qkv_b.items()
            }
        layers = {
            t: to_device(
                a, True, specs["layers"][t] if specs is not None else None
            )
            for t, a in qkv_stacked.items()
        }
        layers.update({
            t: to_device(
                a, False,
                specs["layers"][t] if specs is not None else None,
            )
            for t, a in qkvb_stacked.items()
        })
        layers.update(
            wo=stacked("wo", npre + "attention.dense.weight", True),
            wo_b=stacked(
                "wo_b", npre + "attention.dense.bias", False, False
            ),
            w_up=stacked("w_up", npre + "mlp.dense_h_to_4h.weight", True),
            w_up_b=stacked(
                "w_up_b", npre + "mlp.dense_h_to_4h.bias", False, False
            ),
            w_down=stacked(
                "w_down", npre + "mlp.dense_4h_to_h.weight", True
            ),
            w_down_b=stacked(
                "w_down_b", npre + "mlp.dense_4h_to_h.bias", False, False
            ),
            attn_norm=stacked(
                "attn_norm", npre + "input_layernorm.weight", False, False
            ),
            attn_norm_b=stacked(
                "attn_norm_b", npre + "input_layernorm.bias", False, False
            ),
            mlp_norm=stacked(
                "mlp_norm", npre + "post_attention_layernorm.weight",
                False, False,
            ),
            mlp_norm_b=stacked(
                "mlp_norm_b", npre + "post_attention_layernorm.bias",
                False, False,
            ),
        )
        sp = specs if specs is not None else {}
        with jax.default_device(cpu):
            head = jnp.swapaxes(src.get("embed_out.weight"), -1, -2)
        params = {
            "embed": to_device(
                src.get("gpt_neox.embed_in.weight"), False, sp.get("embed")
            ),
            "layers": layers,
            "final_norm": to_device(
                src.get("gpt_neox.final_layer_norm.weight"), False,
                sp.get("final_norm"),
            ),
            "final_norm_b": to_device(
                src.get("gpt_neox.final_layer_norm.bias"), False,
                sp.get("final_norm_b"),
            ),
            "lm_head": to_device(head, True, sp.get("lm_head")),
        }
        if logger is not None:
            logger.infof(
                "loaded HF gpt_neox checkpoint from %s (%d layers%s)",
                path, cfg.n_layers, f", {quant}" if quant else "",
            )
        return params

    pre = "model.layers.{}."
    layers = {
        "wq": stacked("wq", pre + "self_attn.q_proj.weight", True),
        "wk": stacked("wk", pre + "self_attn.k_proj.weight", True),
        "wv": stacked("wv", pre + "self_attn.v_proj.weight", True),
        "wo": stacked("wo", pre + "self_attn.o_proj.weight", True),
        "attn_norm": stacked(
            "attn_norm", pre + "input_layernorm.weight", False, False
        ),
        "mlp_norm": stacked(
            "mlp_norm", pre + "post_attention_layernorm.weight", False, False
        ),
    }
    if cfg.attn_bias:
        layers.update(
            wq_b=stacked("wq_b", pre + "self_attn.q_proj.bias", False, False),
            wk_b=stacked("wk_b", pre + "self_attn.k_proj.bias", False, False),
            wv_b=stacked("wv_b", pre + "self_attn.v_proj.bias", False, False),
        )
    if cfg.is_moe:
        moe = "model.layers.{i}.block_sparse_moe."
        layers.update(
            router=stacked(
                "router", "model.layers.{}.block_sparse_moe.gate.weight",
                True, quantize=False,  # tiny and routing-sensitive
            ),
            # Mixtral naming: w1=gate, w3=up, w2=down.
            w_gate=stacked_experts("w_gate", moe + "experts.{e}.w1.weight"),
            w_up=stacked_experts("w_up", moe + "experts.{e}.w3.weight"),
            w_down=stacked_experts("w_down", moe + "experts.{e}.w2.weight"),
        )
    else:
        layers.update(
            w_gate=stacked("w_gate", pre + "mlp.gate_proj.weight", True),
            w_up=stacked("w_up", pre + "mlp.up_proj.weight", True),
            w_down=stacked("w_down", pre + "mlp.down_proj.weight", True),
        )
    e_spec = specs["embed"] if specs is not None else None
    h_spec = specs["lm_head"] if specs is not None else None
    embed = to_device(src.get("model.embed_tokens.weight"), False, e_spec)
    if "lm_head.weight" in src:
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            head = jnp.swapaxes(src.get("lm_head.weight"), -1, -2)
        lm_head = to_device(head, True, h_spec)
    else:  # tie_word_embeddings
        lm_head = to_device(
            jnp.swapaxes(src.get("model.embed_tokens.weight"), -1, -2),
            True, h_spec,
        )
    params = {
        "embed": embed,
        "layers": layers,
        "final_norm": to_device(
            src.get("model.norm.weight"), False,
            specs["final_norm"] if specs is not None else None,
        ),
        "lm_head": lm_head,
    }
    if logger is not None:
        logger.infof(
            "loaded HF llama checkpoint from %s (%d layers%s)",
            path, cfg.n_layers, f", {quant}" if quant else "",
        )
    return params


def params_have_q8(params: Any) -> bool:
    return params_quant_mode(params) == "int8"


def params_quant_mode(params: Any) -> str:
    """"int8" / "int4" / "" — detect pre-quantized param trees."""
    import jax

    from gofr_tpu.ops.quant import Q4, Q8

    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, (Q4, Q8))
    ):
        if isinstance(leaf, Q8):
            return "int8"
        if isinstance(leaf, Q4):
            return "int4"
    return ""
