"""Device-program builders + profiling for the LLM serving engine.

``_build_llm_steps`` compiles the jitted prefill/decode/spec/mega
programs (the entire device-side serving dataplane); profile_decode
measures them. Mixin methods on InferenceEngine — split from
``engine.py`` along its build/profile seams (r4 VERDICT weak #10)."""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Optional

import numpy as np


class LLMProgramsMixin:
    """Jitted-program construction + device profiling."""

    # -- the mixin contract (mypy strict scope) ------------------------
    # Provided by InferenceEngine.__init__ / _init_llm_serving_state;
    # declared so the strict type gate checks this module's own logic
    # against a written-down contract (the SchedulerMixin idiom).
    _jax: Any
    _jnp: Any
    cfg: Any
    mesh: Any
    tokenizer: Any
    cache: Any
    params: Any
    quant: str
    family: str
    _running: bool
    _seed: int
    _top_k: int
    enable_top_p: bool
    enable_penalties: bool
    top_logprobs: int
    spec_tokens: int
    n_slots: int
    window_k: int
    prefill_batch: int
    prefill_chunk: int
    _slot_state_dirty: bool
    _up: Any  # host→device placement callable
    _compiles: Any  # serving.device_telemetry.CompileTracker
    # Device-resident slot planes (jax arrays).
    _tokens_dev: Any
    _logps_dev: Any
    _nsteps_dev: Any
    _seeds_dev: Any
    _noff_dev: Any
    _aids_dev: Any
    _pcounts_dev: Any
    _fpen_dev: Any
    _ppen_dev: Any
    _bidx_dev: Any
    _bval_dev: Any
    _topi_dev: Any
    _topl_dev: Any
    # Compiled-program callables (built below, compile-tracked).
    _prefill_chunk_step: Any
    _prefill_chunk_step_hist: Any
    _prefill_multi_chunk: Any
    _prefill_multi_chunk_hist: Any
    _decode_window: Any
    _mega_window: Any
    _spec_window: Any
    _mega_spec_window: Any

    def _build_llm_steps(self) -> None:
        jax, jnp = self._jax, self._jnp
        from gofr_tpu.models.transformer import (
            transformer_decode_step,
            transformer_prefill_chunk,
        )
        cfg, top_k = self.cfg, self._top_k
        # pallas kernels don't auto-partition under GSPMD: mesh-sharded
        # serving takes the dense attention formulations, which XLA
        # partitions (per-head locality under tp; sharded-softmax
        # collectives under cp).
        dense_attn = self.mesh is not None

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            _rep_sh = NamedSharding(self.mesh, PartitionSpec())

            def rep(x: Any) -> Any:
                # Host-fetched outputs must be REPLICATED: on a multi-host
                # (DCN) mesh every process np.asarray()s its local shard,
                # which is only the full value if the sharding says so.
                return jax.lax.with_sharding_constraint(x, _rep_sh)
        else:
            def rep(x: Any) -> Any:
                return x

        enable_top_p = self.enable_top_p
        enable_penalties = self.enable_penalties
        top_lp_k = self.top_logprobs

        def sample(
            logits: Any, keys: Any, temps: Any, greedy: Any,
            topps: Any, pen: Optional[tuple] = None,
            bias: Optional[tuple] = None,
        ) -> tuple:
            """Returns (token, logprob) — the logprob is the log-softmax at
            the chosen token of the distribution the choice was made from
            (the model's own when no penalties apply), the number the
            OpenAI logprobs field reports.

            pen: optional (counts [rows, V] int32, fpen [rows], ppen
            [rows]) — OpenAI-style frequency/presence penalties over the
            GENERATED tokens (prompt tokens don't count, the vLLM
            convention), applied before greedy argmax AND sampling so
            temperature-0 requests honor them too."""
            logits = logits.astype(jnp.float32)
            if bias is not None:
                # OpenAI logit_bias: sparse per-request (token, bias)
                # pairs, padded with idx -1. Applied to the raw logits —
                # before penalties, greedy argmax, and sampling.
                bidx, bval = bias
                rows = jnp.arange(logits.shape[0])[:, None]
                logits = logits.at[rows, jnp.clip(bidx, 0)].add(
                    jnp.where(bidx >= 0, bval, 0.0)
                )
            if pen is not None:
                counts, fpen, ppen = pen
                cf = counts.astype(jnp.float32)
                logits = (
                    logits
                    - fpen[:, None] * cf
                    - ppen[:, None] * (cf > 0).astype(jnp.float32)
                )
            greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temps, 1e-4)[:, None]
            sorted_l = None
            if top_k > 0:
                sorted_l = jnp.sort(scaled, axis=-1)[:, ::-1]
                kth = sorted_l[:, top_k - 1][:, None]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            if enable_top_p:
                # Per-slot nucleus: keep the smallest prefix of the
                # sorted distribution with cumulative prob >= top_p
                # (slots at top_p=1.0 are untouched).
                if sorted_l is not None:
                    # Post-top_k sorted logits are the already-sorted
                    # list with positions >= top_k masked — no second
                    # vocab-wide sort on the decode hot path.
                    V = sorted_l.shape[-1]
                    sorted_p = jnp.where(
                        jnp.arange(V)[None, :] < top_k, sorted_l, -jnp.inf
                    )
                else:
                    sorted_p = jnp.sort(scaled, axis=-1)[:, ::-1]
                cum = jnp.cumsum(jax.nn.softmax(sorted_p, axis=-1), axis=-1)
                # Guarantee the predicate holds somewhere: fp32 cumsum
                # over a big vocab can top out just below a top_p≈1,
                # and argmax over all-False would return 0 — silently
                # collapsing the request to greedy.
                cum = cum.at[:, -1].set(2.0)
                cut_idx = jnp.argmax(cum >= topps[:, None], axis=-1)
                cutoff = jnp.take_along_axis(
                    sorted_p, cut_idx[:, None], axis=-1
                )
                scaled = jnp.where(
                    (topps < 1.0)[:, None] & (scaled < cutoff),
                    -jnp.inf, scaled,
                )
            sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(
                jnp.int32
            )
            chosen = jnp.where(greedy, greedy_tok, sampled)
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            logp = jnp.take_along_axis(logp_all, chosen[:, None], axis=-1)[:, 0]
            if top_lp_k:
                # OpenAI top_logprobs alternatives, from the same
                # (biased/penalized) distribution the choice used.
                tl, ti = jax.lax.top_k(logp_all, top_lp_k)
                return chosen, logp, ti.astype(jnp.int32), tl
            return chosen, logp, None, None

        # Per-request reproducible sampling: each sampled token's key is
        # fold_in(fold_in(engine_base, request_seed), n_sampled_so_far) —
        # counter-based, so a seeded stream is identical regardless of
        # batch composition, window size, or mega/pipelined scheduling.
        base_key = jax.random.PRNGKey(self._seed + 2)

        def row_keys(seeds: Any, nsteps: Any) -> Any:
            def one(sd: Any, n: Any) -> Any:
                return jax.random.fold_in(
                    jax.random.fold_in(base_key, sd), n
                )

            return jax.vmap(one)(seeds, nsteps)

        def _prefill_core(
            params: Any, cache: Any, tokens: Any, slots: Any, starts: Any,
            lens: Any, finalize: Any, row_valid: Any, temps: Any,
            greedy: Any, topps: Any, seeds: Any, all_tokens: Any,
            all_logps: Any, pcounts: Any, nsteps: Any, bidx: Any,
            bval: Any, topi: Any, topl: Any, aids: Any, noff: Any,
            use_bias: bool,
        ) -> tuple:
            """One [P, c] chunk: write K/V + attend; on rows whose prompt
            finishes (finalize) sample the first token and merge it into
            the decode token vector ON DEVICE. Padding rows duplicate row 0
            (identical K/V writes are idempotent; the merge below is
            per-slot select, not scatter, so duplicates can't race).
            pcounts: per-slot generated-token counts (penalties feature) —
            finalize RESETS the slot's row (new request) and counts the
            first sampled token; the first token itself is never penalized
            (its counts are the zeros just written)."""
            logits, cache = transformer_prefill_chunk(
                params, tokens, cache, slots, starts, lens, cfg,
                dense_attn=dense_attn, aids=aids[slots],
            )
            # Sample at the slot's counter OFFSET (noff): 0 for fresh
            # admissions, the delivered-token count for replayed requests
            # — so a non-greedy stream carried across a restart continues
            # on the same counter-based sample path (seeded-sampling
            # replay continuity).
            sub = row_keys(seeds[slots], noff[slots])
            first, first_lp, ftopi, ftopl = sample(
                logits, sub, temps, greedy, topps,
                bias=(bidx[slots], bval[slots]) if use_bias else None,
            )
            S = all_tokens.shape[0]
            match = (
                (jnp.arange(S)[:, None] == slots[None, :])
                & finalize[None, :] & row_valid[None, :]
            )  # [S, P]
            has = jnp.any(match, axis=1)
            idx = jnp.argmax(match, axis=1)
            all_tokens = jnp.where(has, first[idx], all_tokens)
            all_logps = jnp.where(has, first_lp[idx], all_logps)
            cache = cache._replace(
                lengths=jnp.where(has, (starts + lens)[idx], cache.lengths)
            )
            if enable_penalties:
                pcounts = jnp.where(has[:, None], 0, pcounts)
                pcounts = pcounts.at[
                    jnp.arange(S), all_tokens
                ].add(has.astype(jnp.int32))
            # The finalize token was sampled with n=noff; the slot's next
            # sample uses n=noff+1 (fresh requests: 0 then 1).
            nsteps = jnp.where(has, noff + 1, nsteps)
            if top_lp_k:
                topi = jnp.where(has[:, None], ftopi[idx], topi)
                topl = jnp.where(has[:, None], ftopl[idx], topl)
                return (cache, all_tokens, all_logps, rep(first),
                        rep(first_lp), pcounts, nsteps, topi, topl,
                        rep(ftopi), rep(ftopl))
            return (cache, all_tokens, all_logps, rep(first), rep(first_lp),
                    pcounts, nsteps, topi, topl, None, None)

        prefill_chunk_step = partial(
            jax.jit, donate_argnums=(1, 12, 13, 14, 15, 18, 19),
            static_argnames=("use_bias",),
        )(_prefill_core)

        def _multi_chunk_core(
            params: Any, cache: Any, tokens3: Any, slots: Any,
            starts0: Any, n_chunks: Any, history: Any, aids: Any,
        ) -> tuple:
            """Up to D FULL (non-finalizing) [P, c] chunks in ONE dispatch
            — the long-prompt TTFT amortizer: through a network-attached
            relay every chunk dispatch costs a host↔device RTT, so an 8k
            prompt at c=256 pays ~32 RTTs (~2.3 s) without this. No
            sampling and no lengths update happen here (both belong to
            the finalize chunk, which always runs via the single-chunk
            step); history recording (speculation) mirrors
            prefill_chunk_step_hist. tokens3: [D, P, c]; n_chunks ≤ D is
            a runtime operand, so one compile serves every prompt length."""
            D, Pb, c = tokens3.shape

            def cond(s: tuple) -> Any:
                return s[0] < n_chunks

            def body(s: tuple) -> tuple:
                i, cache, history = s
                toks = jax.lax.dynamic_index_in_dim(
                    tokens3, i, 0, keepdims=False
                )
                starts = starts0 + i * c
                lens = jnp.full((Pb,), c, jnp.int32)
                _, cache = transformer_prefill_chunk(
                    params, toks, cache, slots, starts, lens, cfg,
                    dense_attn=dense_attn, aids=aids[slots],
                )
                if history is not None:
                    hpos = jnp.clip(
                        starts[:, None] + jnp.arange(c)[None, :], 0,
                        history.shape[1] - 1,
                    )
                    history = history.at[slots[:, None], hpos].set(toks)
                return i + 1, cache, history

            _, cache, history = jax.lax.while_loop(
                cond, body, (jnp.asarray(0, jnp.int32), cache, history)
            )
            return cache, history

        @partial(jax.jit, donate_argnums=(1,))
        def prefill_multi_chunk(
            params: Any, cache: Any, tokens3: Any, slots: Any,
            starts0: Any, n_chunks: Any, aids: Any,
        ) -> Any:
            cache, _ = _multi_chunk_core(
                params, cache, tokens3, slots, starts0, n_chunks, None, aids
            )
            return cache

        @partial(jax.jit, donate_argnums=(1, 6))
        def prefill_multi_chunk_hist(
            params: Any, cache: Any, tokens3: Any, slots: Any,
            starts0: Any, n_chunks: Any, history: Any, aids: Any,
        ) -> tuple:
            return _multi_chunk_core(
                params, cache, tokens3, slots, starts0, n_chunks, history,
                aids,
            )

        @partial(
            jax.jit, donate_argnums=(1, 12, 13, 14, 15, 18, 19, 22),
            static_argnames=("use_bias",),
        )
        def prefill_chunk_step_hist(
            params: Any, cache: Any, tokens: Any, slots: Any, starts: Any,
            lens: Any, finalize: Any, row_valid: Any, temps: Any,
            greedy: Any, topps: Any, seeds: Any, all_tokens: Any,
            all_logps: Any, pcounts: Any, nsteps: Any, bidx: Any,
            bval: Any, topi: Any, topl: Any, aids: Any, noff: Any,
            history: Any, use_bias: bool = False,
        ) -> tuple:
            """Prefill + record the chunk's tokens into the draft history
            (speculation on). Padding rows duplicate row 0 — idempotent."""
            out = _prefill_core(
                params, cache, tokens, slots, starts, lens, finalize,
                row_valid, temps, greedy, topps, seeds, all_tokens,
                all_logps, pcounts, nsteps, bidx, bval, topi, topl, aids,
                noff, use_bias,
            )
            c = tokens.shape[1]
            hpos = jnp.clip(
                starts[:, None] + jnp.arange(c)[None, :], 0,
                history.shape[1] - 1,
            )
            history = history.at[slots[:, None], hpos].set(tokens)
            return out + (history,)

        def make_decode_body(
            params: Any, active: Any, temps: Any, greedy: Any, topps: Any,
            fpen: Any, ppen: Any, seeds: Any, bidx: Any, bval: Any,
            use_bias: bool, aids: Any,
        ) -> Any:
            """One decode step (scan body): forward + sample + penalty
            count scatter — shared by the plain window and the mega
            while_loop so the two dispatch modes cannot drift."""

            def body(carry: tuple, _: Any) -> tuple:
                tokens, logps, cache, nsteps, pcounts, topi, topl = carry
                logits, cache = transformer_decode_step(
                    params, tokens, cache, active, cfg,
                    dense_attn=dense_attn, aids=aids,
                )
                pen = (pcounts, fpen, ppen) if enable_penalties else None
                sub = row_keys(seeds, nsteps)
                nxt, nlp, ntopi, ntopl = sample(
                    logits, sub, temps, greedy, topps, pen,
                    bias=(bidx, bval) if use_bias else None,
                )
                nsteps = nsteps + active.astype(jnp.int32)
                if enable_penalties:
                    pcounts = pcounts.at[
                        jnp.arange(nxt.shape[0]), nxt
                    ].add(active.astype(jnp.int32))
                # Alternatives travel WITH their token: the carried planes
                # belong to the token entering this step (ys), the fresh
                # ones to the token just chosen (next carry).
                ys = (tokens, logps, topi, topl) if top_lp_k else (
                    tokens, logps
                )
                if not top_lp_k:
                    ntopi, ntopl = topi, topl
                return (nxt, nlp, cache, nsteps, pcounts, ntopi, ntopl), ys

            return body

        @partial(
            jax.jit, static_argnames=("k", "use_bias"),
            donate_argnums=(3, 5, 11, 15, 16),
        )
        def decode_window(
            params: Any, tokens: Any, logps: Any, cache: Any, active: Any,
            nsteps: Any, temps: Any, greedy: Any, topps: Any, fpen: Any,
            ppen: Any, pcounts: Any, seeds: Any, bidx: Any, bval: Any,
            topi: Any, topl: Any, aids: Any, k: int, use_bias: bool,
        ) -> tuple:
            """Run k decode steps entirely on device; emit the k
            (token, logprob) pairs that ENTER each step (so a freshly
            prefilled slot's first token is emitted by its first window)
            and carry the (k+1)-th as next input. One host fetch per k
            tokens — emitted tokens and logprobs pack into ONE [2, k, S]
            f32 block (token ids are exact in f32 below 2^24) so the
            host↔device roundtrip count stays one per window. Sampling
            keys are counter-based — nsteps threads through ON DEVICE and
            the seeds plane uploads only on admission — so steady-state
            dispatch uploads nothing host→device at all."""
            body = make_decode_body(params, active, temps, greedy, topps,
                                    fpen, ppen, seeds, bidx, bval, use_bias,
                                    aids)
            (final, final_lp, cache, nsteps, pcounts, topi, topl), ys = (
                jax.lax.scan(
                    body,
                    (tokens, logps, cache, nsteps, pcounts, topi, topl),
                    length=k,
                )
            )
            if top_lp_k:
                etoks, elps, etopi, etopl = ys
                etops = rep(jnp.stack([etopi.astype(jnp.float32), etopl]))
            else:
                etoks, elps = ys
                etops = None
            emitted = jnp.stack([etoks.astype(jnp.float32), elps])
            return (rep(emitted), etops, final, final_lp, cache, nsteps,
                    pcounts, topi, topl)

        eos_id = self.tokenizer.eos_id if self.tokenizer is not None else -1

        @partial(
            jax.jit, static_argnames=("k", "m", "use_bias"),
            donate_argnums=(3, 5, 11, 15, 16),
        )
        def mega_window(
            params: Any, tokens: Any, logps: Any, cache: Any, active: Any,
            nsteps: Any, temps: Any, greedy: Any, topps: Any, fpen: Any,
            ppen: Any, pcounts: Any, seeds: Any, bidx: Any, bval: Any,
            topi: Any, topl: Any, remaining: Any, eos_stop: Any,
            aids: Any, k: int, m: int, use_bias: bool,
        ) -> tuple:
            """Up to m k-step windows in ONE dispatch. A device-side
            while_loop runs windows until every slot's `remaining` budget
            is covered (decremented k per window; zeroed when the slot
            emits EOS and `eos_stop` holds) or m windows have run. Emits
            into a fixed [2, m*k, S] buffer; entries past the returned
            windows_run*k are untouched zeros the host must not read.
            Slots whose budget ran out while others continue keep
            computing junk tokens — their cache writes land past their
            retired region (scatter drops OOB; paged lookups park at
            block 0) and the host drops the tokens post-retirement, so
            the junk is slot-local by construction."""
            body = make_decode_body(params, active, temps, greedy, topps,
                                    fpen, ppen, seeds, bidx, bval, use_bias,
                                    aids)
            S = tokens.shape[0]
            emitted0 = jnp.zeros((2, m * k, S), dtype=jnp.float32)
            etops0 = (
                jnp.zeros((2, m * k, S, top_lp_k), dtype=jnp.float32)
                if top_lp_k else jnp.zeros((0,), dtype=jnp.float32)
            )

            def win_body(state: tuple) -> tuple:
                (w, tokens, logps, cache, nsteps, pcounts, remaining,
                 emitted, etops, topi, topl) = state
                ((tokens, logps, cache, nsteps, pcounts, topi, topl),
                 ys) = jax.lax.scan(
                    body,
                    (tokens, logps, cache, nsteps, pcounts, topi, topl),
                    length=k,
                )
                if top_lp_k:
                    etoks, elps, etopi, etopl = ys
                    etops = jax.lax.dynamic_update_slice(
                        etops,
                        jnp.stack([etopi.astype(jnp.float32), etopl]),
                        (0, w * k, 0, 0),
                    )
                else:
                    etoks, elps = ys
                slab = jnp.stack([etoks.astype(jnp.float32), elps])
                emitted = jax.lax.dynamic_update_slice(
                    emitted, slab, (0, w * k, 0)
                )
                hit = jnp.any(etoks == eos_id, axis=0) & eos_stop
                remaining = jnp.where(hit, 0, jnp.maximum(remaining - k, 0))
                return (w + 1, tokens, logps, cache, nsteps, pcounts,
                        remaining, emitted, etops, topi, topl)

            def win_cond(state: tuple) -> Any:
                return (state[0] < m) & jnp.any(state[6] > 0)

            (w, final, final_lp, cache, nsteps, pcounts, _, emitted, etops,
             topi, topl) = jax.lax.while_loop(
                win_cond, win_body,
                (jnp.asarray(0, jnp.int32), tokens, logps, cache,
                 nsteps, pcounts, remaining, emitted0, etops0, topi, topl),
            )
            return (rep(emitted), rep(etops) if top_lp_k else None, rep(w),
                    final, final_lp, cache, nsteps, pcounts, topi, topl)

        G = self.spec_tokens

        def make_spec_body(
            params: Any, active: Any, temps: Any, greedy: Any, topps: Any,
            seeds: Any, bidx: Any, bval: Any, use_bias: bool, aids: Any,
        ) -> Any:
            """One speculative step (scan body), shared by the plain spec
            window and the mega-spec while_loop.

            Numerics-exact verify: the G+1 candidate positions run through
            ``transformer_decode_step`` — the SAME program the spec-off
            decode window scans — in an inner scan, so every position's
            logits have the decode step's accumulation shape and reduction
            order and are bit-identical to what a spec-off engine would
            compute at that stream position. (The previous design verified
            all positions in one batched ``[S, G+1]`` forward whose bf16
            reduction order differed, flipping near-tie argmaxes — the
            ROADMAP direction-1 blocker this replaces; graftlint GL025 now
            flags that bug class statically.) Each inner step commits its
            K/V and advances ``lengths`` exactly like plain decode; after
            the scan the step rewinds ``lengths`` to the accepted count, so
            writes past it are junk beyond the live region — never
            attended, overwritten by the next step (the commit_chunk_kv
            discipline, inherited for free).

            Because verification IS the decode-step + shared ``sample``
            closure (counter-based keys at the same stream offsets),
            acceptance extends beyond greedy: a seeded-SAMPLED slot accepts
            a draft token when the categorical draw at that position picks
            it, and per-request ``logit_bias`` rides through the same
            ``use_bias`` compile variant the decode window uses — both
            byte-identical to spec=0 by the same construction."""
            from gofr_tpu.models.transformer import (
                ngram_draft,
                transformer_decode_step,
            )

            def body(carry: tuple, _: Any) -> tuple:
                tokens, logps, cache, nsteps, history = carry
                draft = ngram_draft(history, cache.lengths, tokens, G)
                inputs = jnp.concatenate([tokens[:, None], draft], axis=1)
                lengths0 = cache.lengths

                def pos_body(pcarry: tuple, tok_j: Any) -> tuple:
                    cache_i, n_i = pcarry
                    logits, cache_i = transformer_decode_step(
                        params, tok_j, cache_i, active, cfg,
                        dense_attn=dense_attn, aids=aids,
                    )
                    sub = row_keys(seeds, n_i)
                    nxt, nlp, _, _ = sample(
                        logits, sub, temps, greedy, topps,
                        bias=(bidx, bval) if use_bias else None,
                    )
                    return (
                        (cache_i, n_i + active.astype(jnp.int32)),
                        (nxt, nlp),
                    )

                (cache, _), (chosen_s, chosen_lp_s) = jax.lax.scan(
                    pos_body, (cache, nsteps), inputs.T
                )
                chosen = chosen_s.T  # [S, G+1] — position j's TRUE token
                chosen_lp = chosen_lp_s.T
                # Accept the longest prefix of drafts that match the token
                # the decode-step program actually chose at each position
                # (greedy slots: the exact argmax; sampled slots: the exact
                # counter-keyed categorical draw — both identical to the
                # spec=0 stream by construction, so acceptance is lossless
                # for EVERY slot, not just greedy ones).
                match = draft == chosen[:, :G]
                acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
                counts = jnp.where(active, acc + 1, 0)
                bonus = jnp.take_along_axis(chosen, acc[:, None], axis=1)[:, 0]
                bonus_lp = jnp.take_along_axis(
                    chosen_lp, acc[:, None], axis=1
                )[:, 0]
                step_tokens = inputs  # [S, G+1]; first `counts` are emitted
                # Position j's emitted logprob is the one its token was
                # CHOSEN with at position j-1 (accepted ⇒ draft == chosen).
                step_logps = jnp.concatenate(
                    [logps[:, None], chosen_lp[:, :G]], axis=1
                )
                # History: current+accepted drafts at len..len+acc, bonus at
                # len+counts — the invariant "current token sits at
                # history[lengths]" holds into the next step. Rejected
                # drafts and inactive slots park at max_len-1 (XLA scatter
                # is nondeterministic on duplicate indices, so the rejected
                # entries must not share a position with the bonus write;
                # history[max_len-1] garbage only ever wastes a draft).
                S2, T = history.shape
                hvals = jnp.concatenate([inputs, bonus[:, None]], axis=1)
                hpos = lengths0[:, None] + jnp.arange(G + 2)[None, :]
                hpos = hpos.at[:, G + 1].set(lengths0 + counts)
                keep = jnp.concatenate(
                    [
                        jnp.arange(G + 1)[None, :] <= acc[:, None],
                        jnp.ones((S2, 1), dtype=bool),
                    ],
                    axis=1,
                )
                keep = keep & active[:, None]
                hpos = jnp.where(keep, jnp.minimum(hpos, T - 1), T - 1)
                history = history.at[
                    jnp.arange(S2)[:, None], hpos
                ].set(hvals)
                # The inner scan advanced lengths by G+1 per active slot;
                # the stream only accepted `counts`. Rewind — junk K/V
                # above lengths0+counts is never attended and the next
                # step's decode writes overwrite it in order.
                cache = cache._replace(lengths=lengths0 + counts)
                nsteps = nsteps + counts
                return (
                    (bonus, bonus_lp, cache, nsteps, history),
                    (step_tokens, step_logps, counts),
                )

            return body

        @partial(
            jax.jit, static_argnames=("k", "use_bias"),
            donate_argnums=(3, 5, 9),
        )
        def spec_window(
            params: Any, tokens: Any, logps: Any, cache: Any, active: Any,
            nsteps: Any, temps: Any, greedy: Any, topps: Any,
            history: Any, seeds: Any, bidx: Any, bval: Any, aids: Any,
            k: int, use_bias: bool,
        ) -> tuple:
            """k speculative steps on device. Each step drafts G tokens by
            n-gram lookup in the slot's own history, verifies draft+current
            by running the DECODE-STEP program over the G+1 positions
            (bit-exact vs spec=0 — see make_spec_body), accepts the longest
            prefix matching the program's own choices (greedy AND sampled
            slots), and carries the bonus token. Emits per step: tokens
            [S, G+1] (= the step's inputs), logps, and counts [S]
            (=accepted+1 valid entries)."""
            body = make_spec_body(params, active, temps, greedy, topps,
                                  seeds, bidx, bval, use_bias, aids)
            ((final, final_lp, cache, nsteps, history),
             (etoks, elps, ecnt)) = jax.lax.scan(
                body, (tokens, logps, cache, nsteps, history), length=k
            )
            emitted = jnp.stack(
                [etoks.astype(jnp.float32), elps]
            )  # [2, k, S, G+1]
            return (rep(emitted), rep(ecnt), final, final_lp, cache, nsteps,
                    history)

        @partial(
            jax.jit, static_argnames=("k", "m", "use_bias"),
            donate_argnums=(3, 5, 9),
        )
        def mega_spec_window(
            params: Any, tokens: Any, logps: Any, cache: Any, active: Any,
            nsteps: Any, temps: Any, greedy: Any, topps: Any,
            history: Any, seeds: Any, bidx: Any, bval: Any,
            remaining: Any, eos_stop: Any,
            aids: Any, k: int, m: int, use_bias: bool,
        ) -> tuple:
            """Mega × speculation: up to m k-step spec windows in ONE
            dispatch. `remaining` decrements by the ACTUAL emitted token
            counts (speculation emits ≥ k per window per live slot, so
            coverage ≥ the plain-decode guarantee); EOS detection scans
            only the VALID (first `counts`) entries of each step —
            rejected draft positions must not zero a budget."""
            body = make_spec_body(params, active, temps, greedy, topps,
                                  seeds, bidx, bval, use_bias, aids)
            S = tokens.shape[0]
            emitted0 = jnp.zeros((2, m * k, S, G + 1), dtype=jnp.float32)
            ecnt0 = jnp.zeros((m * k, S), dtype=jnp.int32)

            def win_body(state: tuple) -> tuple:
                (w, tokens, logps, cache, nsteps, history, remaining,
                 emitted, ecnt) = state
                ((tokens, logps, cache, nsteps, history),
                 (etoks, elps, cnts)) = jax.lax.scan(
                    body, (tokens, logps, cache, nsteps, history), length=k
                )
                slab = jnp.stack([etoks.astype(jnp.float32), elps])
                emitted = jax.lax.dynamic_update_slice(
                    emitted, slab, (0, w * k, 0, 0)
                )
                ecnt = jax.lax.dynamic_update_slice(
                    ecnt, cnts.astype(jnp.int32), (w * k, 0)
                )
                valid = (
                    jnp.arange(G + 1)[None, None, :] < cnts[:, :, None]
                )  # [k, S, G+1]
                hit = (
                    ((etoks == eos_id) & valid).any(axis=(0, 2)) & eos_stop
                )
                delivered = cnts.sum(axis=0).astype(jnp.int32)  # [S]
                remaining = jnp.where(
                    hit, 0, jnp.maximum(remaining - delivered, 0)
                )
                return (w + 1, tokens, logps, cache, nsteps, history,
                        remaining, emitted, ecnt)

            def win_cond(state: tuple) -> Any:
                return (state[0] < m) & jnp.any(state[6] > 0)

            ((w, final, final_lp, cache, nsteps, history, _, emitted,
              ecnt)) = jax.lax.while_loop(
                win_cond, win_body,
                (jnp.asarray(0, jnp.int32), tokens, logps, cache, nsteps,
                 history, remaining, emitted0, ecnt0),
            )
            return (rep(emitted), rep(ecnt), rep(w), final, final_lp, cache,
                    nsteps, history)

        # Compile tracking (serving/device_telemetry.py): every serving
        # program is wrapped so each XLA cache growth counts under its
        # program name — and a compile after the warm-up fence bumps
        # the steady-state recompile counter, the dynamic twin of
        # graftlint GL015's static jit-in-request-path check.
        wrap = self._compiles.wrap
        self._prefill_chunk_step = wrap("prefill_chunk", prefill_chunk_step)
        self._prefill_chunk_step_hist = wrap(
            "prefill_chunk_hist", prefill_chunk_step_hist
        )
        self._prefill_multi_chunk = wrap(
            "prefill_multi_chunk", prefill_multi_chunk
        )
        self._prefill_multi_chunk_hist = wrap(
            "prefill_multi_chunk_hist", prefill_multi_chunk_hist
        )
        self._decode_window = wrap("decode_window", decode_window)
        self._mega_window = wrap("mega_window", mega_window)
        self._spec_window = wrap("spec_window", spec_window)
        self._mega_spec_window = wrap("mega_spec_window", mega_spec_window)


    # ------------------------------------------------------------------
    # profiling (bench harness; VERDICT r1 weak #4 — know where time goes)
    # ------------------------------------------------------------------

    def profile_decode(
        self, n_windows: int = 8, prompt_len: int = 16
    ) -> dict:
        """Measure device-only decode window time and the host↔device fetch
        RTT, with the engine stopped. Chains ``n_windows`` windows
        back-to-back with one final block, so the relay RTT amortizes out:
        ``window_s ≈ (total - rtt) / n_windows``.

        Returns ``{"window_s", "step_s", "rtt_s", "prefill_s"}``.
        """
        if self.family != "llm":
            raise RuntimeError("profile_decode is for llm engines")
        if self._running:
            raise RuntimeError("stop the engine before profiling")
        jax, jnp = self._jax, self._jnp
        B, P = self.n_slots, self.prefill_batch
        prompt_len = min(prompt_len, self.prefill_chunk)

        # Prefill ALL slots via chunk steps so decode reads realistic KV
        # prefixes. Timed on the last call (first pays compile).
        prefill_s = 0.0
        for base in range(0, B, P):
            rows = list(range(base, min(base + P, B)))
            tokens = np.ones((P, self.prefill_chunk), dtype=np.int32)
            slots = np.full((P,), rows[0], dtype=np.int32)
            slots[: len(rows)] = rows
            starts = np.zeros((P,), dtype=np.int32)
            lens = np.full((P,), prompt_len, dtype=np.int32)
            finalize = np.ones((P,), dtype=bool)
            row_valid = np.zeros((P,), dtype=bool)
            row_valid[: len(rows)] = True
            temps = np.ones((P,), dtype=np.float32)
            topps = np.ones((P,), dtype=np.float32)
            greedy = np.ones((P,), dtype=bool)
            t0 = time.perf_counter()
            (self.cache, self._tokens_dev, self._logps_dev, first, _flp,
             self._pcounts_dev, self._nsteps_dev, self._topi_dev,
             self._topl_dev, _fti, _ftl) = (
                self._prefill_chunk_step(
                    self.params, self.cache, self._up(tokens),
                    self._up(slots), self._up(starts), self._up(lens),
                    self._up(finalize), self._up(row_valid),
                    self._up(temps), self._up(greedy),
                    self._up(topps),
                    self._seeds_dev, self._tokens_dev, self._logps_dev,
                    self._pcounts_dev, self._nsteps_dev, self._bidx_dev,
                    self._bval_dev, self._topi_dev, self._topl_dev,
                    self._aids_dev, self._noff_dev,
                    use_bias=False,
                )
            )
            jax.block_until_ready(first)
            prefill_s = time.perf_counter() - t0

        # Fresh [B]-shaped vectors — the prefill loop's temps/greedy above
        # are [P]-shaped and P != B crashes the decode window.
        active = jnp.ones((B,), dtype=bool)
        tdev = jnp.ones((B,), dtype=jnp.float32)
        pdev = jnp.ones((B,), dtype=jnp.float32)
        gdev = jnp.ones((B,), dtype=bool)

        def window() -> Any:
            out = self._decode_window(
                self.params, self._tokens_dev, self._logps_dev, self.cache,
                active, self._nsteps_dev, tdev, gdev, pdev,
                self._fpen_dev, self._ppen_dev, self._pcounts_dev,
                self._seeds_dev, self._bidx_dev, self._bval_dev,
                self._topi_dev, self._topl_dev, self._aids_dev,
                k=self.window_k, use_bias=False,
            )
            (emitted, _etops, self._tokens_dev, self._logps_dev, self.cache,
             self._nsteps_dev, self._pcounts_dev, self._topi_dev,
             self._topl_dev) = out
            return emitted

        # Warmup (compile) + RTT probe: a blocking fetch of a just-computed
        # tiny array is ~one relay roundtrip.
        jax.block_until_ready(window())
        rtts = []
        for _ in range(5):
            x = self._tokens_dev + 1
            t0 = time.perf_counter()
            np.asarray(x)
            rtts.append(time.perf_counter() - t0)
        rtt_s = sorted(rtts)[len(rtts) // 2]

        t0 = time.perf_counter()
        last = None
        for _ in range(n_windows):
            last = window()
        jax.block_until_ready(last)
        total = time.perf_counter() - t0
        window_s = max(total - rtt_s, 1e-9) / n_windows

        # Reset cache lengths so profiling state can't leak into serving.
        self.cache = self.cache._replace(
            lengths=jnp.zeros_like(self.cache.lengths)
        )
        self._slot_state_dirty = True
        return {
            "window_s": window_s,
            "step_s": window_s / self.window_k,
            "rtt_s": rtt_s,
            "prefill_s": prefill_s,
        }

    def param_bytes(self) -> int:
        from gofr_tpu.ops.quant import quantized_bytes

        return quantized_bytes(self.params)

