"""OpenAI-compatible serving surface (net-new; no reference analog).

``add_openai_routes(app)`` registers the three endpoints LLM clients
expect, backed by the container's TPU engine:

* ``POST /v1/completions`` — prompt in, text out; ``"stream": true``
  switches to SSE chunks (``data: {...}\\n\\n`` … ``data: [DONE]``).
* ``POST /v1/chat/completions`` — messages in, assistant message out;
  same streaming contract.
* ``GET /v1/models`` — the model registry.

Responses use the OpenAI wire shapes directly (``Raw`` / ``Stream``
bypass the framework's ``{"data": ...}`` envelope), so off-the-shelf
OpenAI SDKs can point their ``base_url`` at this server. Chat messages
render through the model's OWN chat template when the configured HF
tokenizer carries one (``apply_chat_template``, token-id output so BOS
isn't doubled), falling back to a minimal role-tagged flattening; an
explicit ``chat_template`` arg to ``add_openai_routes`` overrides both.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, AsyncIterator, Callable, Optional, Union

from gofr_tpu.errors import GofrError
from gofr_tpu.http.response import Raw, Stream


class OpenAIRequestError(GofrError):
    """400 with a plain message (OpenAI clients show error.message)."""

    status_code = 400


class OpenAIModelNotFound(GofrError):
    """404 — the OpenAI wire code for requesting a model that isn't
    loaded (clients silently getting a DIFFERENT model's output would
    be worse than the error)."""

    status_code = 404


def default_chat_template(messages: list[dict]) -> str:
    """Minimal generic chat flattening (role-tagged lines + cue)."""
    lines = []
    for m in messages:
        role = m.get("role", "user")
        lines.append(f"{role}: {m.get('content', '')}")
    lines.append("assistant:")
    return "\n".join(lines)


def _completion_body(req_json: bytes) -> dict:
    try:
        body = json.loads(req_json or b"{}")
    except json.JSONDecodeError as exc:
        raise OpenAIRequestError(f"invalid JSON body: {exc}") from None
    if not isinstance(body, dict):
        raise OpenAIRequestError("request body must be a JSON object")
    return body


def _usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


_MAX_N = 16  # choices per request; unbounded n is a one-request DoS


def _stop_list(body: dict) -> list[str]:
    stop = body.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if not (isinstance(stop, list) and all(isinstance(s, str) for s in stop)):
        raise OpenAIRequestError("stop must be a string or list of strings")
    if len(stop) > 4:
        raise OpenAIRequestError("stop supports at most 4 sequences")
    if any(not s for s in stop):
        raise OpenAIRequestError("stop sequences must be non-empty")
    return stop


def _n_choices(body: dict, streaming: bool) -> int:
    n = body.get("n")
    n = 1 if n is None else int(n)  # NOT `or`: n=0 must reach validation
    if n < 1 or n > _MAX_N:
        raise OpenAIRequestError(f"n must be between 1 and {_MAX_N}")
    if streaming and n > 1:
        raise OpenAIRequestError("streaming supports n=1")
    return n


def _decoder(engine: Any) -> Callable[[int], str]:
    if engine.tokenizer:
        return lambda t: engine.tokenizer.decode([t])
    return lambda t: ""


def _completion_logprobs(engine: Any, result: Any) -> dict:
    """OpenAI completions logprobs block."""
    dec = _decoder(engine)
    tokens = [dec(t) for t in result.token_ids]
    top: Optional[list[dict]] = None
    if result.token_top_logprobs is not None:
        # Keyed by decoded token STRING per the completions schema; when
        # two ids decode identically, the FIRST (highest logprob — alts
        # are sorted descending) wins.
        top = []
        for alts in result.token_top_logprobs:
            d: dict = {}
            for t, lp in (alts or []):
                d.setdefault(dec(t), round(lp, 6))
            top.append(d)
    return {
        "tokens": tokens,
        "token_logprobs": [round(lp, 6) for lp in result.token_logprobs],
        "top_logprobs": top,
        "text_offset": None,
    }


def add_openai_routes(
    app: Any,
    chat_template: Optional[Callable[[list[dict]], str]] = None,
) -> None:
    """Register /v1/* OpenAI-compatible routes on a gofr_tpu App."""
    template = chat_template or default_chat_template

    def _engine(ctx: Any) -> Any:
        engine = getattr(ctx.container, "tpu", None)
        if engine is None:
            raise OpenAIRequestError(
                "no TPU engine configured (set TPU_ENABLED/TPU_MODEL)"
            )
        return engine

    def _check_model(body: dict, engine: Any) -> str:
        """A request naming a model that is NOT the loaded one gets the
        OpenAI 404, not the loaded model's output. A loaded LoRA
        adapter's name IS a model here (the vLLM convention): the
        request runs on the base engine with that adapter's slot
        selected per-request — one batch serves many adapters.
        Returns the adapter name ("" = base)."""
        want = body.get("model")
        if not want or want == engine.model_name:
            return ""
        names = engine.lora_names() if hasattr(engine, "lora_names") else []
        if want in names:
            return str(want)
        raise OpenAIModelNotFound(
            f"model {want!r} is not loaded (serving "
            f"{engine.model_name!r}); GET /v1/models lists "
            f"availability"
        )

    def _lifecycle(ctx: Any) -> dict:
        """Deadline (X-Request-Timeout) + cancel token (disconnect) from
        the HTTP server, threaded into every engine submit so abandoned
        or expired requests retire mid-decode and free their KV blocks.
        X-Tenant-Id rides along for per-tenant admission quotas
        (TPU_TENANT_QUEUE_MAX), and the tracer middleware's span becomes
        the engine timeline's parent (one trace from socket to token —
        and across replicas: a pool forwards it on HTTPReplica calls)."""
        header = getattr(ctx, "header", None)
        tenant = (header("x-tenant-id") if header is not None else "") or ""
        # Brownout SLO class (X-SLO-Class: interactive|standard|batch):
        # under overload the engine sheds batch-class admissions first
        # and interactive last (serving/brownout.py). Unknown values
        # fall back to the tenant default, then "standard" — never 400.
        slo_class = (
            header("x-slo-class") if header is not None else ""
        ) or ""
        out = dict(
            deadline=ctx.deadline, cancel=ctx.cancel_token, tenant=tenant,
            slo_class=slo_class,
        )
        span = ctx.get("span") if hasattr(ctx, "get") else None
        if span is not None and hasattr(span, "traceparent"):
            out["traceparent"] = span.traceparent()
        return out

    def _params(body: dict) -> dict:
        # Explicit nulls are legal per the OpenAI spec → fall back to
        # defaults instead of int(None)/float(None) crashes.
        max_tokens = body.get("max_tokens")
        if max_tokens is None:
            max_tokens = body.get("max_completion_tokens")
        temperature = body.get("temperature")
        temperature = 1.0 if temperature is None else float(temperature)
        top_p = body.get("top_p")
        top_p = 1.0 if top_p is None else float(top_p)
        if top_p == 0.0:
            # OpenAI accepts top_p=0 (smallest nucleus = the argmax
            # token); map it to plain greedy so it works on engines
            # compiled without the nucleus sampler too. Negative values
            # stay invalid and flow through to the engine's 400.
            top_p, temperature = 1.0, 0.0
        fpen = body.get("frequency_penalty")
        ppen = body.get("presence_penalty")
        seed = body.get("seed")
        logit_bias = body.get("logit_bias")
        return dict(
            max_new_tokens=128 if max_tokens is None else int(max_tokens),
            temperature=temperature,
            top_p=top_p,
            stop_on_eos=True,
            frequency_penalty=0.0 if fpen is None else float(fpen),
            presence_penalty=0.0 if ppen is None else float(ppen),
            seed=None if seed is None else int(seed),
            logit_bias=logit_bias or None,
        )

    def _stream_response(
        engine: Any, prompt: Any, params: dict, *, rid: str, model: str,
        chat: bool,
        stop_seqs: Optional[list[str]] = None, include_usage: bool = False,
        include_tokens: bool = False,
    ) -> Stream:
        # ``stream_options.include_tokens`` (this repo's extension, the
        # replica tier's internal wire): every chunk carries the raw
        # ``token_ids`` drained since the previous chunk — even when the
        # text is held back (UTF-8 tail / stop-sequence window) — and
        # the finish chunk carries ``prompt_tokens``. A routing tier
        # consuming the stream re-decodes text itself; what it needs on
        # the wire is the exact delivered-token prefix, so a replica
        # that dies mid-stream can resume on a sibling byte-identically.
        # Submit BEFORE returning the Stream: prompt validation
        # (ErrorPromptTooLong → 413 etc.) must fail the request proper,
        # not die silently after the 200/SSE headers are on the wire.
        # Stop sequences go to the ENGINE too, so decoding halts and the
        # KV slot frees at the match instead of running out the budget.
        req = engine.submit_generate(
            prompt, stop=list(stop_seqs or []), **params
        )
        object_name = (
            "chat.completion.chunk" if chat else "text_completion"
        )
        stops = stop_seqs or []

        async def events() -> AsyncIterator[str]:
            created = int(time.time())
            loop = asyncio.get_running_loop()
            emitted_ids: list[int] = []
            sent_tokens = 0  # ids already attached to a yielded chunk
            printed = ""
            reason = "stop"

            def payload_of(text: str) -> dict:
                nonlocal sent_tokens
                payload = (
                    {"delta": {"content": text}, "index": 0}
                    if chat else {"text": text, "index": 0}
                )
                if include_tokens:
                    payload["token_ids"] = emitted_ids[sent_tokens:]
                    sent_tokens = len(emitted_ids)
                return payload

            def stop_hit(full: str) -> int:
                return min(
                    (at for at in (full.find(s) for s in stops) if at != -1),
                    default=-1,
                )

            try:
                if chat:
                    first = {"role": "assistant", "content": ""}
                    yield _sse(rid, object_name, model, created,
                               {"delta": first, "index": 0})
                # Hold back enough text that a stop sequence can never be
                # emitted before it is detected (a stop spanning two
                # deltas must still cut cleanly).
                hold = max((len(s) for s in stops), default=0)
                stopped = False
                while not stopped:
                    tok = await loop.run_in_executor(None, req.stream.get)
                    if tok is None:
                        break
                    emitted_ids.append(tok)
                    if engine.tokenizer is None:
                        if include_tokens:
                            # Token-id wire with no text surface: the
                            # consumer (a routing tier) decodes itself.
                            yield _sse(rid, object_name, model, created,
                                       payload_of(""))
                        continue
                    # Cumulative decode: per-token decode would split
                    # multi-byte UTF-8 / BPE merges.
                    full = engine.tokenizer.decode(emitted_ids)
                    at = stop_hit(full)
                    if at != -1:
                        full = full[:at]
                        stopped = True
                    elif full.endswith("�"):
                        # Possibly incomplete UTF-8 tail — hold back
                        # (the ids still flow when the consumer asked
                        # for them: delivered-prefix accounting must
                        # not lag the generation).
                        if include_tokens:
                            yield _sse(rid, object_name, model, created,
                                       payload_of(""))
                        continue
                    else:
                        full = full[: max(len(printed), len(full) - hold)]
                    if len(full) > len(printed):
                        text, printed = full[len(printed):], full
                        yield _sse(rid, object_name, model, created,
                                   payload_of(text))
                    elif include_tokens:
                        yield _sse(rid, object_name, model, created,
                                   payload_of(""))
                brownout_flag = False
                if stopped:
                    reason = "stop"
                else:
                    # The engine's retired result is authoritative: its
                    # text is already stop-trimmed, its finish_reason
                    # covers eos/budget/context-window.
                    try:
                        result = req.future.result(timeout=30)
                    except Exception as exc:  # noqa: BLE001 — mapped to a terminal SSE error event below
                        # Terminal error event: a deadline-exceeded or
                        # engine-failed stream must END with an explicit
                        # error, not silently truncate (the 200/SSE
                        # headers are long gone, so the event stream is
                        # the only error channel left).
                        err = {
                            "error": {
                                "message": str(exc),
                                "type": type(exc).__name__,
                                "code": getattr(exc, "status_code", 500),
                            }
                        }
                        yield f"data: {json.dumps(err)}\n\n"
                        yield "data: [DONE]\n\n"
                        return
                    reason = result.finish_reason
                    # The retired result is the brownout-clamp
                    # authority too: set only when the clamp actually
                    # cut the answer, and carried across replicas (a
                    # pool fronting a REMOTE engine gets the flag from
                    # the remote's finish chunk via GenerationResult,
                    # where the local handle's brownout_clamped is
                    # never stamped).
                    brownout_flag = bool(
                        getattr(result, "brownout", False)
                    )
                    if (
                        engine.tokenizer is not None
                        and len(result.text) > len(printed)
                    ):
                        yield _sse(rid, object_name, model, created,
                                   payload_of(result.text[len(printed):]))
                done = (
                    {"delta": {}, "index": 0, "finish_reason": reason}
                    if chat else
                    {"text": "", "index": 0, "finish_reason": reason}
                )
                if brownout_flag:
                    # Deliberate policy truncation rides the finish
                    # chunk.
                    done["brownout"] = True
                if include_tokens:
                    # Any ids still unattached (final flush) ride the
                    # finish chunk, plus the prompt length so the
                    # consumer can build its usage accounting without a
                    # second round trip.
                    done["token_ids"] = emitted_ids[sent_tokens:]
                    sent_tokens = len(emitted_ids)
                    done["prompt_tokens"] = len(req.prompt_ids)
                yield _sse(rid, object_name, model, created, done)
                if include_usage:
                    # stream_options.include_usage: one final chunk with
                    # empty choices and the usage block (OpenAI wire).
                    # The retired result's trimmed token list is the
                    # authoritative count (the SSE loop drains tokens
                    # past a stop cut before detecting it).
                    try:
                        n_out = len(
                            req.future.result(timeout=30).token_ids
                        )
                    except Exception:  # noqa: BLE001 — cancelled stream
                        n_out = len(emitted_ids)
                    usage_chunk = {
                        "id": rid,
                        "object": object_name,
                        "created": created,
                        "model": model,
                        "choices": [],
                        "usage": _usage(len(req.prompt_ids), n_out),
                    }
                    yield f"data: {json.dumps(usage_chunk)}\n\n"
                yield "data: [DONE]\n\n"
            finally:
                # Client disconnected (GeneratorExit via the server's
                # aclose), stop sequence hit, or completed: cancel so the
                # engine frees the KV slot instead of decoding for nobody
                # (cancel_request also trips the shared CancelToken the
                # scheduler's lifecycle reap watches).
                req.cancel_request()

        return Stream(chunks=events())

    def _sse(
        rid: str, object_name: str, model: str, created: int, choice: dict
    ) -> str:
        return "data: " + json.dumps({
            "id": rid,
            "object": object_name,
            "created": created,
            "model": model,
            "choices": [choice],
        }) + "\n\n"

    def _normalize_prompts(prompt: Any) -> list:
        """OpenAI ``prompt`` forms: str, [int] (token ids), [str] /
        [[int]] (a batch — one completion per element)."""
        if isinstance(prompt, str):
            return [prompt]
        if isinstance(prompt, list):
            if not prompt:
                raise OpenAIRequestError("prompt must not be empty")
            if all(isinstance(p, int) for p in prompt):
                return [prompt]  # one prompt as token ids
            if all(isinstance(p, str) for p in prompt) or all(
                isinstance(p, list) and all(isinstance(t, int) for t in p)
                for p in prompt
            ):
                return list(prompt)
        raise OpenAIRequestError(
            "prompt must be a string, token-id array, or batch thereof"
        )

    @app.post("/v1/completions")
    async def completions(ctx: Any) -> Union[Raw, Stream]:
        engine = _engine(ctx)
        body = _completion_body(ctx.request.raw.body)
        adapter = _check_model(body, engine)
        prompts = _normalize_prompts(body.get("prompt", ""))
        params = dict(_params(body), adapter=adapter, **_lifecycle(ctx))
        stop_seqs = _stop_list(body)
        streaming = bool(body.get("stream"))
        n = _n_choices(body, streaming)
        rid = f"cmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", engine.model_name)
        if streaming:
            if len(prompts) > 1:
                raise OpenAIRequestError(
                    "streaming supports a single prompt per request"
                )
            if body.get("echo"):
                raise OpenAIRequestError(
                    "echo is not supported with streaming"
                )
            return _stream_response(
                engine, prompts[0], params, rid=rid, model=model, chat=False,
                stop_seqs=stop_seqs,
                include_usage=bool(
                    (body.get("stream_options") or {}).get("include_usage")
                ),
                include_tokens=bool(
                    (body.get("stream_options") or {}).get("include_tokens")
                ),
            )
        lp_req = body.get("logprobs")
        want_logprobs = lp_req not in (None, False, 0)
        if (want_logprobs and isinstance(lp_req, int)
                and not isinstance(lp_req, bool) and lp_req >= 1):
            # completions semantics: logprobs=N → N alternatives/token,
            # CLAMPED to what the engine compiled (requests that were
            # valid before TPU_TOP_LOGPROBS existed must not start
            # 400ing: engines without the feature return null
            # alternatives as before).
            eng_k = getattr(engine, "top_logprobs", 0)
            if eng_k:
                params = dict(params, top_logprobs=min(int(lp_req), eng_k))
        echo = bool(body.get("echo"))
        results = await asyncio.gather(
            *(engine.generate(p, stop=stop_seqs, **params)
              for p in prompts for _ in range(n))
        )
        choices = []
        req_prompts = [p for p in prompts for _ in range(n)]
        for i, r in enumerate(results):
            # The engine trims text/tokens at the stop match and reports
            # finish_reason itself, so logprobs stay text-aligned.
            text = r.text
            if echo:
                # OpenAI legacy `echo`: prompt text prepended to the
                # completion (logprobs stay completion-only — prompt
                # logprob capture is not supported).
                pr = req_prompts[i]
                if not isinstance(pr, str):
                    if engine.tokenizer is None:
                        raise OpenAIRequestError(
                            "echo with token-id prompts needs a tokenizer"
                        )
                    pr = engine.tokenizer.decode(pr)
                text = pr + text
            choice = {
                "text": text,
                "index": i,
                "logprobs": _completion_logprobs(engine, r)
                if want_logprobs else None,
                "finish_reason": r.finish_reason,
            }
            if getattr(r, "brownout", False):
                # Deliberate overload truncation (brownout L1 clamp):
                # advertised so clients can distinguish policy from a
                # short completion. Absent entirely outside a brownout
                # — the nominal wire shape is byte-identical.
                choice["brownout"] = True
            choices.append(choice)
        return Raw({
            "id": rid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": model,
            "choices": choices,
            "usage": _usage(
                sum(r.prompt_tokens for r in results),
                sum(len(r.token_ids) for r in results),
            ),
        }, status=200)

    @app.post("/v1/chat/completions")
    async def chat_completions(ctx: Any) -> Union[Raw, Stream]:
        engine = _engine(ctx)
        body = _completion_body(ctx.request.raw.body)
        adapter = _check_model(body, engine)
        messages = body.get("messages") or []
        if not isinstance(messages, list) or not messages:
            raise OpenAIRequestError("messages must be a non-empty list")
        # Prefer the model's own chat template (HF tokenizers carry one);
        # fall back to the generic role-tagged flattening. An explicit
        # chat_template arg to add_openai_routes overrides both.
        if chat_template is None and hasattr(
            engine.tokenizer, "apply_chat_template"
        ):
            try:
                prompt = engine.tokenizer.apply_chat_template(messages)
            except Exception:  # noqa: BLE001 — template may reject roles
                prompt = template(messages)
        else:
            prompt = template(messages)
        params = dict(_params(body), adapter=adapter, **_lifecycle(ctx))
        stop_seqs = _stop_list(body)
        streaming = bool(body.get("stream"))
        n = _n_choices(body, streaming)
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", engine.model_name)
        if streaming:
            return _stream_response(
                engine, prompt, params, rid=rid, model=model, chat=True,
                stop_seqs=stop_seqs,
                include_usage=bool(
                    (body.get("stream_options") or {}).get("include_usage")
                ),
                include_tokens=bool(
                    (body.get("stream_options") or {}).get("include_tokens")
                ),
            )
        want_logprobs = bool(body.get("logprobs"))
        chat_top = body.get("top_logprobs")
        if want_logprobs and chat_top:
            # Clamp to the engine's compiled K — pre-flag requests that
            # passed top_logprobs must keep getting 200s with empty
            # alternatives on engines without the feature.
            eng_k = getattr(engine, "top_logprobs", 0)
            if eng_k:
                params = dict(
                    params, top_logprobs=min(int(chat_top), eng_k)
                )
        results = await asyncio.gather(
            *(engine.generate(prompt, stop=stop_seqs, **params)
              for _ in range(n))
        )
        choices = []
        for i, r in enumerate(results):
            choice: dict = {
                "index": i,
                "message": {"role": "assistant", "content": r.text},
                "finish_reason": r.finish_reason,
            }
            if getattr(r, "brownout", False):
                # Deliberate overload truncation (brownout L1 clamp).
                choice["brownout"] = True
            if want_logprobs:
                dec = _decoder(engine)
                tops = r.token_top_logprobs or [None] * len(r.token_ids)
                choice["logprobs"] = {"content": [
                    {
                        "token": dec(t),
                        "logprob": round(lp, 6),
                        "top_logprobs": [
                            {"token": dec(at), "logprob": round(alp, 6)}
                            for at, alp in (alts or [])
                        ],
                    }
                    for t, lp, alts in zip(
                        r.token_ids, r.token_logprobs, tops
                    )
                ]}
            choices.append(choice)
        return Raw({
            "id": rid,
            "object": "chat.completion",
            "created": int(time.time()),
            "model": model,
            "choices": choices,
            "usage": _usage(
                sum(r.prompt_tokens for r in results),
                sum(len(r.token_ids) for r in results),
            ),
        }, status=200)

    @app.post("/v1/embeddings")
    async def embeddings(ctx: Any) -> Raw:
        """OpenAI embeddings: served by the secondary encoder engine
        (``TPU_EMBED_MODEL``), or by the primary when it IS an encoder."""
        engine = getattr(ctx.container, "tpu_embed", None)
        if engine is None:
            primary = getattr(ctx.container, "tpu", None)
            if primary is not None and primary.family == "encoder":
                engine = primary
        if engine is None:
            raise OpenAIRequestError(
                "no encoder engine configured (set TPU_EMBED_MODEL, or "
                "TPU_MODEL to an encoder like bert-base)"
            )
        body = _completion_body(ctx.request.raw.body)
        _check_model(body, engine)
        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        if (
            not isinstance(inputs, list) or not inputs
            or not all(isinstance(t, str) for t in inputs)
        ):
            raise OpenAIRequestError(
                "input must be a string or a non-empty list of strings"
            )
        vecs = await asyncio.gather(*(engine.embed(t) for t in inputs))
        data = [
            {
                "object": "embedding",
                "embedding": [float(x) for x in v],
                "index": i,
            }
            for i, v in enumerate(vecs)
        ]
        n_tokens = sum(
            min(len(engine.tokenizer.encode(t)), engine.max_len)
            if engine.tokenizer else 0
            for t in inputs
        )
        return Raw({
            "object": "list",
            "data": data,
            "model": body.get("model", engine.model_name),
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        }, status=200)  # OpenAI wire-compat: POST answers 200

    @app.get("/v1/models")
    async def models(ctx: Any) -> Raw:
        from gofr_tpu.models.registry import list_models

        engine: Any = getattr(ctx.container, "tpu", None)
        embedder: Any = getattr(ctx.container, "tpu_embed", None)
        loaded = {
            e.model_name for e in (engine, embedder) if e is not None
        }
        adapters = (
            engine.lora_names()
            if engine is not None and hasattr(engine, "lora_names") else []
        )
        return Raw({
            "object": "list",
            "data": [
                {
                    "id": name,
                    "object": "model",
                    "owned_by": "gofr-tpu",
                    "loaded": name in loaded,
                }
                for name in list_models()
            ] + [
                # Loaded LoRA adapters are servable model ids (request
                # them via the "model" field; vLLM convention).
                {
                    "id": name,
                    "object": "model",
                    "owned_by": "gofr-tpu",
                    "loaded": True,
                    "parent": engine.model_name,
                }
                for name in adapters
            ],
        })
