"""OpenAI-compatible serving surface (net-new; no reference analog).

``add_openai_routes(app)`` registers the three endpoints LLM clients
expect, backed by the container's TPU engine:

* ``POST /v1/completions`` — prompt in, text out; ``"stream": true``
  switches to SSE chunks (``data: {...}\\n\\n`` … ``data: [DONE]``).
* ``POST /v1/chat/completions`` — messages in, assistant message out;
  same streaming contract.
* ``GET /v1/models`` — the model registry.

Responses use the OpenAI wire shapes directly (``Raw`` / ``Stream``
bypass the framework's ``{"data": ...}`` envelope), so off-the-shelf
OpenAI SDKs can point their ``base_url`` at this server. Chat messages
are flattened with a minimal generic template; models loaded from HF
checkpoints with their own chat template should pre-format prompts
client-side or override ``chat_template``.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, Callable, Optional

from gofr_tpu.errors import GofrError
from gofr_tpu.http.response import Raw, Stream


class OpenAIRequestError(GofrError):
    """400 with a plain message (OpenAI clients show error.message)."""

    status_code = 400


def default_chat_template(messages: list[dict]) -> str:
    """Minimal generic chat flattening (role-tagged lines + cue)."""
    lines = []
    for m in messages:
        role = m.get("role", "user")
        lines.append(f"{role}: {m.get('content', '')}")
    lines.append("assistant:")
    return "\n".join(lines)


def _completion_body(req_json: bytes) -> dict:
    try:
        body = json.loads(req_json or b"{}")
    except json.JSONDecodeError as exc:
        raise OpenAIRequestError(f"invalid JSON body: {exc}") from None
    if not isinstance(body, dict):
        raise OpenAIRequestError("request body must be a JSON object")
    return body


def _usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def add_openai_routes(
    app,
    chat_template: Optional[Callable[[list[dict]], str]] = None,
) -> None:
    """Register /v1/* OpenAI-compatible routes on a gofr_tpu App."""
    template = chat_template or default_chat_template

    def _engine(ctx):
        engine = getattr(ctx.container, "tpu", None)
        if engine is None:
            raise OpenAIRequestError(
                "no TPU engine configured (set TPU_ENABLED/TPU_MODEL)"
            )
        return engine

    def _params(body: dict) -> dict:
        # Explicit nulls are legal per the OpenAI spec → fall back to
        # defaults instead of int(None)/float(None) crashes.
        max_tokens = body.get("max_tokens")
        if max_tokens is None:
            max_tokens = body.get("max_completion_tokens")
        temperature = body.get("temperature")
        return dict(
            max_new_tokens=128 if max_tokens is None else int(max_tokens),
            temperature=1.0 if temperature is None else float(temperature),
            stop_on_eos=True,
        )

    def _stream_response(
        engine, prompt, params: dict, *, rid: str, model: str, chat: bool,
    ) -> Stream:
        # Submit BEFORE returning the Stream: prompt validation
        # (ErrorPromptTooLong → 413 etc.) must fail the request proper,
        # not die silently after the 200/SSE headers are on the wire.
        req = engine.submit_generate(prompt, **params)
        object_name = (
            "chat.completion.chunk" if chat else "text_completion"
        )

        async def events():
            created = int(time.time())
            loop = asyncio.get_running_loop()
            emitted_ids: list[int] = []
            printed = ""
            try:
                if chat:
                    first = {"role": "assistant", "content": ""}
                    yield _sse(rid, object_name, model, created,
                               {"delta": first, "index": 0})
                while True:
                    tok = await loop.run_in_executor(None, req.stream.get)
                    if tok is None:
                        break
                    emitted_ids.append(tok)
                    if engine.tokenizer is None:
                        text = ""
                    else:
                        # Cumulative decode: per-token decode would split
                        # multi-byte UTF-8 / BPE merges. Hold back while
                        # the tail is an incomplete sequence (U+FFFD).
                        full = engine.tokenizer.decode(emitted_ids)
                        if full.endswith("�"):
                            continue
                        text, printed = full[len(printed):], full
                    payload = (
                        {"delta": {"content": text}, "index": 0}
                        if chat else {"text": text, "index": 0}
                    )
                    yield _sse(rid, object_name, model, created, payload)
                # Flush any held-back tail (genuinely invalid bytes stay
                # U+FFFD; emit them now that the stream is over).
                if engine.tokenizer is not None and emitted_ids:
                    full = engine.tokenizer.decode(emitted_ids)
                    if full != printed:
                        tail = full[len(printed):]
                        payload = (
                            {"delta": {"content": tail}, "index": 0}
                            if chat else {"text": tail, "index": 0}
                        )
                        yield _sse(rid, object_name, model, created, payload)
                done = (
                    {"delta": {}, "index": 0, "finish_reason": "stop"}
                    if chat else
                    {"text": "", "index": 0, "finish_reason": "stop"}
                )
                yield _sse(rid, object_name, model, created, done)
                yield "data: [DONE]\n\n"
            finally:
                # Client disconnected (GeneratorExit via the server's
                # aclose) or completed: cancel so the engine frees the
                # KV slot instead of decoding to max_tokens for nobody.
                req.future.cancel()

        return Stream(chunks=events())

    def _sse(rid, object_name, model, created, choice) -> str:
        return "data: " + json.dumps({
            "id": rid,
            "object": object_name,
            "created": created,
            "model": model,
            "choices": [choice],
        }) + "\n\n"

    def _normalize_prompts(prompt) -> list:
        """OpenAI ``prompt`` forms: str, [int] (token ids), [str] /
        [[int]] (a batch — one completion per element)."""
        if isinstance(prompt, str):
            return [prompt]
        if isinstance(prompt, list):
            if not prompt:
                raise OpenAIRequestError("prompt must not be empty")
            if all(isinstance(p, int) for p in prompt):
                return [prompt]  # one prompt as token ids
            if all(isinstance(p, str) for p in prompt) or all(
                isinstance(p, list) and all(isinstance(t, int) for t in p)
                for p in prompt
            ):
                return list(prompt)
        raise OpenAIRequestError(
            "prompt must be a string, token-id array, or batch thereof"
        )

    @app.post("/v1/completions")
    async def completions(ctx):  # noqa: ANN001
        engine = _engine(ctx)
        body = _completion_body(ctx.request.raw.body)
        prompts = _normalize_prompts(body.get("prompt", ""))
        params = _params(body)
        rid = f"cmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", engine.model_name)
        if body.get("stream"):
            if len(prompts) > 1:
                raise OpenAIRequestError(
                    "streaming supports a single prompt per request"
                )
            return _stream_response(
                engine, prompts[0], params, rid=rid, model=model, chat=False,
            )
        results = await asyncio.gather(
            *(engine.generate(p, **params) for p in prompts)
        )
        return Raw({
            "id": rid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": model,
            "choices": [
                {
                    "text": r.text,
                    "index": i,
                    "logprobs": None,
                    "finish_reason": "stop",
                }
                for i, r in enumerate(results)
            ],
            "usage": _usage(
                sum(r.prompt_tokens for r in results),
                sum(len(r.token_ids) for r in results),
            ),
        }, status=200)

    @app.post("/v1/chat/completions")
    async def chat_completions(ctx):  # noqa: ANN001
        engine = _engine(ctx)
        body = _completion_body(ctx.request.raw.body)
        messages = body.get("messages") or []
        if not isinstance(messages, list) or not messages:
            raise OpenAIRequestError("messages must be a non-empty list")
        prompt = template(messages)
        params = _params(body)
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", engine.model_name)
        if body.get("stream"):
            return _stream_response(
                engine, prompt, params, rid=rid, model=model, chat=True,
            )
        result = await engine.generate(prompt, **params)
        return Raw({
            "id": rid,
            "object": "chat.completion",
            "created": int(time.time()),
            "model": model,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": result.text},
                "finish_reason": "stop",
            }],
            "usage": _usage(result.prompt_tokens, len(result.token_ids)),
        }, status=200)

    @app.get("/v1/models")
    async def models(ctx):  # noqa: ANN001
        from gofr_tpu.models.registry import list_models

        engine: Any = getattr(ctx.container, "tpu", None)
        return Raw({
            "object": "list",
            "data": [
                {
                    "id": name,
                    "object": "model",
                    "owned_by": "gofr-tpu",
                    "loaded": engine is not None and engine.model_name == name,
                }
                for name in list_models()
            ],
        })
