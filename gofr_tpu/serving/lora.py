"""LoRA adapter ingestion for multi-adapter serving.

Parses HF PEFT adapter checkpoints (``adapter_config.json`` +
``adapter_model.safetensors``) into this framework's stacked-layer leaf
layout: per target projection, ``a: [L, d_in, r]`` / ``b: [L, r, d_out]``
with the PEFT scaling ``lora_alpha / r`` folded into ``b`` (serving never
needs the unscaled factors). The engine writes these into adapter slot
``idx`` of its ``[L, 1+lora_slots, ...]`` device leaves
(:meth:`InferenceEngine.load_lora`).

Design notes (TPU-first): adapters for every request in a batch execute
in ONE compiled program — a per-slot gather over the stacked adapter
axis feeds two rank-space einsums next to each base matmul
(``models/transformer.py:_lora``). Rank is a compile-time constant
(``TPU_LORA_RANK``); adapters with smaller r zero-pad up to it, which is
exact (zero rank-columns contribute nothing).

Reference analog: none — GoFr has no model serving; the integration
shape follows its datasource idiom (config-gated feature, explicit
errors, health surface), ``/root/reference/pkg/gofr/datasource``.
"""

from __future__ import annotations

import glob
import json
import os

# HF PEFT module names → our projection leaves.
PEFT_TARGET_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}

_HF_MODULE = {
    "wq": "self_attn.q_proj",
    "wk": "self_attn.k_proj",
    "wv": "self_attn.v_proj",
    "wo": "self_attn.o_proj",
    "w_gate": "mlp.gate_proj",
    "w_up": "mlp.up_proj",
    "w_down": "mlp.down_proj",
}


def is_peft_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, "adapter_config.json")
    )


def load_peft_adapter(
    path: str,
    cfg,
    rank: int,
    targets: tuple[str, ...],
) -> dict:
    """Load a PEFT adapter dir → ``{target: (a, b)}`` stacked over layers.

    a: [L, d_in, rank] f32→cfg.dtype, b: [L, rank, d_out] with
    ``lora_alpha/r`` folded in. The adapter's r must be ≤ ``rank`` (the
    engine's compiled rank); smaller ranks zero-pad. Adapter targets
    must be a subset of the engine's compiled ``targets``.
    """
    import jax.numpy as jnp
    import numpy as np

    with open(os.path.join(path, "adapter_config.json")) as f:
        acfg = json.load(f)
    r = int(acfg["r"])
    alpha = float(acfg.get("lora_alpha", r))
    scale = alpha / r
    if r > rank:
        raise ValueError(
            f"adapter rank {r} exceeds the engine's compiled "
            f"TPU_LORA_RANK={rank}"
        )
    mod_targets = []
    for m in acfg.get("target_modules", []):
        t = PEFT_TARGET_MAP.get(m)
        if t is None:
            raise ValueError(
                f"unsupported PEFT target module {m!r} "
                f"(supported: {sorted(PEFT_TARGET_MAP)})"
            )
        mod_targets.append(t)
    missing = [t for t in mod_targets if t not in targets]
    if missing:
        raise ValueError(
            f"adapter targets {missing} not compiled into the engine "
            f"(TPU_LORA_TARGETS={','.join(targets)})"
        )

    from safetensors import safe_open

    tensors: dict = {}
    files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for fname in files:
        h = safe_open(fname, framework="numpy")
        for name in h.keys():
            tensors[name] = h.get_tensor(name)

    def find(i: int, t: str, which: str):
        mod = _HF_MODULE[t]
        for pre in (
            f"base_model.model.model.layers.{i}.",
            f"model.layers.{i}.",
        ):
            name = f"{pre}{mod}.lora_{which}.weight"
            if name in tensors:
                return tensors[name]
        return None

    from gofr_tpu.models.transformer import lora_dims

    out = {}
    for t in mod_targets:
        d_in, d_out = lora_dims(cfg, t)
        a = np.zeros((cfg.n_layers, d_in, rank), dtype=np.float32)
        b = np.zeros((cfg.n_layers, rank, d_out), dtype=np.float32)
        found = 0
        for i in range(cfg.n_layers):
            wa = find(i, t, "A")  # [r, d_in]
            wb = find(i, t, "B")  # [d_out, r]
            if wa is None or wb is None:
                continue  # PEFT may skip layers via layers_to_transform
            if wa.shape != (r, d_in) or wb.shape != (d_out, r):
                raise ValueError(
                    f"adapter tensor shape mismatch for layer {i} {t}: "
                    f"A{wa.shape} B{wb.shape}, expected A({r},{d_in}) "
                    f"B({d_out},{r})"
                )
            a[i, :, :r] = wa.T
            b[i, :r, :] = wb.T * scale
            found += 1
        if not found:
            raise ValueError(f"adapter has no tensors for target {t!r}")
        out[t] = (jnp.asarray(a), jnp.asarray(b))
    return out


def validate_adapter_leaves(
    leaves: dict, cfg, rank: int, targets: tuple[str, ...]
) -> None:
    """Shape-check a raw ``{target: (a, b)}`` dict (the non-PEFT source
    form accepted by ``load_lora`` — e.g. adapters trained in-framework)."""
    from gofr_tpu.models.transformer import lora_dims

    for t, (a, b) in leaves.items():
        if t not in targets:
            raise ValueError(
                f"adapter target {t!r} not compiled into the engine "
                f"(TPU_LORA_TARGETS={','.join(targets)})"
            )
        d_in, d_out = lora_dims(cfg, t)
        if tuple(a.shape) != (cfg.n_layers, d_in, rank):
            raise ValueError(
                f"{t} lora A shape {tuple(a.shape)} != "
                f"({cfg.n_layers}, {d_in}, {rank})"
            )
        if tuple(b.shape) != (cfg.n_layers, rank, d_out):
            raise ValueError(
                f"{t} lora B shape {tuple(b.shape)} != "
                f"({cfg.n_layers}, {rank}, {d_out})"
            )
