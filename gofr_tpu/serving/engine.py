"""The TPU inference engine (net-new; SURVEY §2.6).

The container's ``tpu`` member (role of ``gofr.TPU()`` in the north star):
owns the model params on device, the jitted prefill/decode steps, the slot
KV cache, and the scheduler that turns concurrent requests into batched
device executions.

Design:

* **LLM family — continuous batching.** A dedicated scheduler thread admits
  pending prompts into free KV slots (prefill, bucketed padding) and steps
  ALL slots through one fused decode+sample kernel per token. Device-side
  sampling (per-slot temperature array + greedy mask inside the jit) means
  only ``[n_slots] int32`` crosses the host boundary per step. Cache buffers
  are donated so XLA updates them in place.
* **Encoder / vision families — dynamic batching.** Requests coalesce in a
  :class:`DynamicBatcher` (size/deadline flush) and execute as one padded
  batch.
* **Observability** rides the framework metrics registry: queue depth, KV
  slots in use, batch sizes, infer latency, tokens generated, HBM gauges.
"""

from __future__ import annotations

import asyncio
import os
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import numpy as np

from gofr_tpu.serving.batcher import DynamicBatcher, pad_bucket
from gofr_tpu.serving.tokenizer import tokenizer_from_config

_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


# logit_bias entries per request — the OpenAI cap. The [slots, K] planes
# upload only on admission, so K is cheap padding (~77 KB at 32 slots).
LOGIT_BIAS_K = 300


@dataclass
class GenerationResult:
    text: str
    token_ids: list[int]
    prompt_tokens: int
    ttft_s: float
    duration_s: float
    truncated: bool = False  # prompt head dropped (TPU_TRUNCATE_PROMPTS)
    # Model log-softmax at each generated token (OpenAI logprobs field).
    token_logprobs: list[float] = field(default_factory=list)
    # "stop" (eos or a stop sequence matched) | "length" (token budget or
    # context window exhausted).
    finish_reason: str = "stop"
    # Per-token [(token_id, logprob), ...] alternatives when the request
    # asked for top_logprobs (None otherwise).
    token_top_logprobs: "Optional[list]" = None

    @property
    def tokens_per_sec(self) -> float:
        gen = max(len(self.token_ids), 1)
        return gen / self.duration_s if self.duration_s > 0 else 0.0


@dataclass
class _ActiveSeq:
    request: "_GenRequest"
    last_token: int
    n_generated: int = 0
    started_at: float = field(default_factory=time.time)
    first_token_at: Optional[float] = None
    # First token emitted EARLY from the prefill step's async fetch
    # (the decode window that re-emits it skips one position).
    first_emitted: bool = False
    first_skip_done: bool = False
    # Tokens already covered by dispatched windows (starts at 1: the
    # prefill-sampled first token rides the first window). When every
    # active slot's budget is in flight, dispatching more windows is
    # pure overshoot — measured at depth × window_time of wasted device
    # per retirement wave (w16d3: ~0.3 s/wave).
    tokens_in_flight: int = 1


@dataclass
class _GenRequest:
    prompt_ids: list[int]
    max_new_tokens: int
    temperature: float
    stop_on_eos: bool
    top_p: float = 1.0
    stream: "queue.Queue[Optional[int]]" = field(default_factory=queue.Queue)
    future: Future = field(default_factory=Future)
    enqueued_at: float = field(default_factory=time.time)
    token_ids: list[int] = field(default_factory=list)
    token_logprobs: list[float] = field(default_factory=list)
    ttft_s: float = 0.0
    # Prompt length actually in the cache (set at admission; with
    # TPU_TRUNCATE_PROMPTS an overlong prompt keeps its tail and sets
    # ``truncated``; otherwise submit rejects with ErrorPromptTooLong).
    effective_prompt_len: int = 0
    truncated: bool = False
    # True → prefill only, then park the KV rows in the prefix pool and
    # resolve the future with the pool row (serving/prefix_cache.py).
    prefix_store: bool = False
    # Stop sequences: generation retires early when the decoded text
    # contains one; the result is trimmed at the match.
    stop_texts: list[str] = field(default_factory=list)
    # OpenAI-style penalties over generated tokens (TPU_PENALTIES=true).
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # Per-request sampling seed (counter-based keys: same seed + prompt +
    # params → same sampled stream regardless of batch/scheduling).
    seed: int = 0
    # OpenAI logit_bias: {token_id: bias}, at most LOGIT_BIAS_K entries.
    logit_bias: dict = field(default_factory=dict)
    # OpenAI top_logprobs: alternatives per emitted token (≤ engine's
    # compiled TPU_TOP_LOGPROBS).
    top_logprobs: int = 0
    token_top_logprobs: list = field(default_factory=list)
    # Set by _finished when a stop sequence matched: char offset of the
    # earliest match in the decoded text.
    stop_cut: int = -1
    # Multi-LoRA: adapter slot index (0 = base model, no adapter) and
    # the slot's load-generation at submit time (prefix_store requests
    # whose adapter was reloaded/unloaded in flight must not register).
    aid: int = 0
    lora_gen: int = 0


@dataclass
class _PrefillState:
    """A slot mid-chunked-prefill (not yet decoding)."""

    request: _GenRequest
    done: int = 0  # prompt tokens already written to the cache


class InferenceEngine:
    """One loaded model + its serving machinery."""

    def __init__(
        self,
        model_name: str,
        *,
        n_slots: int = 8,
        max_len: int = 1024,
        max_batch: int = 8,
        max_wait_s: float = 0.005,
        window_k: int = 8,
        pipeline_depth: int = 2,
        mega_windows: int = 0,
        prefill_depth: int = 1,
        prefill_chunk: int = 256,
        prefill_batch: int = 8,
        truncate_prompts: bool = False,
        top_k: int = 0,
        enable_top_p: bool = False,
        enable_penalties: bool = False,
        top_logprobs: int = 0,
        spec_tokens: int = 0,
        kv_block: int = 0,
        kv_pool_blocks: int = 0,
        mesh=None,
        quant: str = "",
        kv_quant: str = "",
        prefix_slots: int = 0,
        lora_slots: int = 0,
        lora_rank: int = 16,
        lora_targets: str = "wq,wk,wv,wo",
        params=None,
        logger=None,
        metrics=None,
        tokenizer=None,
        seed: int = 0,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from gofr_tpu.models.registry import get_model

        self._jax, self._jnp = jax, jnp
        self.model_name = model_name
        self.spec = get_model(model_name)
        self.family = self.spec.family
        self.cfg = self.spec.config
        self._logger = logger
        self._metrics = metrics
        self._top_k = top_k
        # Nucleus sampling support is a COMPILE choice: the per-step
        # [slots, vocab] sort only exists in the program when enabled.
        self.enable_top_p = bool(enable_top_p)
        # Frequency/presence penalties are a COMPILE choice too: the
        # [slots, vocab] generated-token count plane and its per-step
        # scatter only exist in the program when enabled.
        self.enable_penalties = bool(enable_penalties)
        if self.enable_penalties and spec_tokens > 0:
            raise ValueError(
                "TPU_PENALTIES and TPU_SPEC_TOKENS are mutually exclusive: "
                "penalties evolve within a step sequence, which breaks the "
                "parallel speculative verify"
            )
        # OpenAI top_logprobs alternatives: a compile choice — the per-
        # step [slots, vocab] top_k only exists in the program when >0.
        self.top_logprobs = max(0, top_logprobs)
        if self.top_logprobs and spec_tokens > 0:
            raise ValueError(
                "TPU_TOP_LOGPROBS and TPU_SPEC_TOKENS are mutually "
                "exclusive (the verify step has no per-emission "
                "alternatives plane)"
            )
        self.tokenizer = tokenizer
        self.mesh = mesh  # multi-chip: NamedSharding placement over ICI

        t0 = time.time()
        self.quant = ""
        if params is not None:
            # Pre-built params (e.g. a real-weights checkpoint loaded via
            # serving/hf_loader, possibly already int8/int4).
            from gofr_tpu.serving.hf_loader import params_quant_mode

            self.params = params
            self.quant = params_quant_mode(params)
        elif mesh is not None and self.family == "llm":
            # Sharded init: params materialize directly onto the mesh with
            # their Megatron-style partition specs — never gathered on one
            # chip (an 8B model doesn't fit one v5e).
            from gofr_tpu.models.transformer import transformer_param_specs
            from gofr_tpu.parallel.sharding import named_shardings, prune_specs

            shardings = named_shardings(
                prune_specs(transformer_param_specs(self.cfg), mesh), mesh
            )
            self.params = jax.jit(
                lambda k: self.spec.init(k, self.cfg), out_shardings=shardings
            )(jax.random.PRNGKey(seed))
        elif (quant or "").lower() in ("int8", "int4") and self.family == "llm":
            # Init DIRECTLY quantized, leaf by leaf: peak HBM is the
            # quantized tree plus one bf16 leaf — llama-3-8b's full bf16
            # tree (~16GB) would not fit a single v5e (VERDICT r1 #4).
            self.quant = (quant or "").lower()
            self.params = self._init_llm_quantized(seed)
        else:
            self.params = self.spec.init(jax.random.PRNGKey(seed), self.cfg)

        if quant and not self.quant:
            self.apply_quantization(quant)

        if logger is not None:
            from gofr_tpu.models.transformer import count_params

            n_params = count_params(self.params)
            logger.infof(
                "model %s initialised: %.2fB params in %.1fs",
                model_name, n_params / 1e9, time.time() - t0,
            )

        self._seed = seed
        self._key = jax.random.PRNGKey(seed + 1)
        self._running = False
        self._draining = False  # graceful stop: reject new, finish live
        self._sched_idle = False  # published by the scheduler, read by drain
        self._fatal: Optional[BaseException] = None  # scheduler death reason
        # Serializes submission against the scheduler's final drain, so a
        # request can never be enqueued after the drain has already run.
        self._submit_lock = threading.Lock()
        self._drained = False

        if self.family == "llm":
            from gofr_tpu.ops.kv_cache import KVCache

            self.max_len = min(max_len, self.cfg.max_len)
            self.n_slots = n_slots
            self.window_k = max(1, window_k)
            self.pipeline_depth = max(1, pipeline_depth)
            # Mega-windows (throughput mode): ONE dispatch runs up to
            # `mega_windows` k-step windows inside a device-side
            # lax.while_loop that early-exits when every slot's remaining
            # budget is covered (or its EOS was emitted). Through a
            # network-attached relay each dispatch costs a full host↔device
            # RTT *in the calling thread*, so at window 8 the RTT is paid
            # every 8 steps (~72 of each ~105 ms wall, measured — r3
            # campaign); one mega dispatch amortizes it over m×k steps.
            # Trade-off: tokens surface per mega-window, not per window —
            # streaming granularity coarsens, so serving defaults keep it
            # off and bursty/offline throughput turns it on.
            self.mega_windows = max(0, mega_windows)
            # Chunked prefill: ONE fixed [prefill_batch, prefill_chunk]
            # compile serves every prompt length, and chunk steps interleave
            # with decode windows so admission never stalls active streams.
            self.prefill_chunk = max(16, min(prefill_chunk, self.max_len))
            self.prefill_batch = max(1, min(prefill_batch, n_slots))
            # Multi-chunk prefill (long-prompt dispatch amortizer): when
            # every prefilling row has ≥2 full chunks left before its
            # finalize chunk, run up to this many chunks per dispatch in
            # a device-side loop. 1 disables (every chunk is its own
            # dispatch — the latency-interleaving default).
            self.prefill_depth = max(1, prefill_depth)
            self.truncate_prompts = truncate_prompts
            # Speculative decoding (n-gram prompt lookup): each device step
            # verifies spec_tokens drafts + 1, so windows can emit up to
            # window_k * (spec_tokens+1) tokens per slot.
            self.spec_tokens = max(0, spec_tokens)
            step_tokens = self.window_k * (self.spec_tokens + 1)
            reserve = 1 + (self.pipeline_depth + 1) * step_tokens
            if self.max_len <= reserve:
                raise ValueError(
                    f"max_len={self.max_len} too small: need > {reserve} "
                    f"(1 + (pipeline_depth+1)*window_k*(spec_tokens+1)) so "
                    f"admission can reserve pipelined-window overshoot "
                    f"room; lower window_k/pipeline_depth/spec_tokens or "
                    f"raise max_len"
                )
            self.kv_quant = (kv_quant or "").lower()
            # Paged KV (TPU_KV_BLOCK>0): block-pool cache + host allocator
            # — HBM scales with resident tokens, not slots × max_len.
            self.kv_block = max(0, kv_block)
            if self.kv_block:
                from gofr_tpu.ops.kv_cache import PagedKVCache

                if self.max_len % self.kv_block:
                    raise ValueError(
                        f"max_len={self.max_len} must be a multiple of "
                        f"kv_block={self.kv_block}"
                    )
                if prefix_slots > 0:
                    raise ValueError(
                        "prefix-KV reuse and the paged cache are mutually "
                        "exclusive (the pool copies slot rows)"
                    )
                make_cache = lambda: PagedKVCache.create(  # noqa: E731
                    self.cfg.n_layers, n_slots, self.max_len,
                    self.cfg.n_kv_heads, self.cfg.head_dim, self.cfg.dtype,
                    quant=self.kv_quant, block=self.kv_block,
                    n_blocks=kv_pool_blocks,
                )
            else:
                make_cache = lambda: KVCache.create(  # noqa: E731
                    self.cfg.n_layers, n_slots, self.max_len,
                    self.cfg.n_kv_heads, self.cfg.head_dim, self.cfg.dtype,
                    quant=self.kv_quant,
                )
            if mesh is not None:
                # KV heads shard over tp, the length axis over cp —
                # same layout prefill and decode.
                from gofr_tpu.models.transformer import kv_cache_specs
                from gofr_tpu.parallel.sharding import (
                    named_shardings,
                    prune_specs,
                )

                self.cache = jax.jit(
                    make_cache,
                    out_shardings=named_shardings(
                        prune_specs(
                            kv_cache_specs(
                                quantized=bool(self.kv_quant),
                                paged=bool(self.kv_block),
                                cp="cp" in mesh.axis_names,
                            ),
                            mesh,
                        ),
                        mesh,
                    ),
                )()
            else:
                self.cache = make_cache()
            if self.kv_block:
                # Host-side block allocator: block 0 is the parking block
                # and never handed out; the table mirror uploads (8 KB)
                # only when an admission/top-up/release dirtied it.
                self._free_blocks = list(range(1, self.cache.n_blocks))
                self._slot_blocks: list[list[int]] = [[] for _ in range(n_slots)]
                self._table_host = np.zeros(
                    (n_slots, self.max_len // self.kv_block), dtype=np.int32
                )
                self._table_dirty = False
                self._dispatched_tokens = [0] * n_slots
            # Prefix-KV reuse: shared system prompts prefill once into a
            # device pool; admission copies rows in (prefix_cache.py).
            self._prefix_pool = None
            if prefix_slots > 0:
                from gofr_tpu.serving.prefix_cache import PrefixPool

                self._prefix_pool = PrefixPool(
                    prefix_slots, self.cache, mesh=mesh
                )
            self._slots: list[Optional[_ActiveSeq]] = [None] * n_slots
            self._prefilling: dict[int, _PrefillState] = {}
            # (first_dev, first_lp_dev, row, slot, seq) awaiting async fetch.
            self._prefill_emits: list = []
            # Paged mode: requests held back waiting for free pool blocks.
            from collections import deque as _deque

            self._wait_kv: "_deque[_GenRequest]" = _deque()
            self._pending: "queue.Queue[_GenRequest]" = queue.Queue(maxsize=1024)
            self._work = threading.Event()
            self._sched: Optional[threading.Thread] = None
            # Host→device uploads: on a mesh, place as a REPLICATED global
            # array — on a multi-host (DCN) mesh a bare jnp.asarray would
            # make a process-local array that cannot feed the global-SPMD
            # jits (every process runs this same code with the same host
            # values, so replicated placement is well-defined).
            if mesh is not None:
                from jax.sharding import (
                    NamedSharding as _NS,
                    PartitionSpec as _P,
                )

                _rep = _NS(mesh, _P())
                self._up = lambda x: jax.device_put(x, _rep)  # noqa: E731
            else:
                self._up = jnp.asarray
            # Multi-PROCESS mesh on a non-TPU backend: serialize device
            # programs. A real TPU core executes one program at a time, so
            # identical per-process launch order is enough for its
            # collectives to pair up; the CPU backend's gloo collectives
            # run on a thread pool, and two in-flight programs (pipelined
            # windows, prefill overlapping decode) interleave their
            # collectives nondeterministically across ranks — observed as
            # gloo "Received data size doesn't match expected size".
            self._lockstep = False
            multiproc = False
            if mesh is not None:
                procs = {d.process_index for d in mesh.devices.flat}
                multiproc = len(procs) > 1
                self._lockstep = (
                    multiproc and jax.default_backend() != "tpu"
                )
            self._tokens_dev = self._up(np.zeros((n_slots,), dtype=np.int32))
            self._logps_dev = self._up(np.zeros((n_slots,), dtype=np.float32))
            # Slot state lives ON DEVICE between windows; re-uploaded only
            # when admissions/retirements change it (dirty flag). Steady-
            # state decode then dispatches with zero host→device traffic.
            # Sampling is counter-based (seed, n_sampled) per slot — no
            # PRNG key threads through device state at all.
            self._nsteps_dev = self._up(np.zeros((n_slots,), dtype=np.int32))
            self._seeds_host = np.zeros((n_slots,), dtype=np.int32)
            self._seeds_dev = self._up(self._seeds_host)
            self._seeds_dirty = False
            # Multi-LoRA adapter plane: per-slot adapter index into the
            # stacked [L, 1+lora_slots, ...] adapter leaves (0 = base).
            # Allocated unconditionally so every compiled signature is
            # uniform; without adapter leaves in params the operand is
            # dead and XLA drops it.
            self._aids_host = np.zeros((n_slots,), dtype=np.int32)
            self._aids_dev = self._up(self._aids_host)
            # Host-side default-seed source for requests without one: each
            # unseeded request gets a fresh draw (OpenAI semantics), while
            # an explicit seed reproduces exactly. Single-process engines
            # mix in boot entropy so restarts/replicas don't replay; a
            # multi-PROCESS mesh keeps the engine-seed-derived stream —
            # every rank must draw IDENTICAL defaults or the SPMD
            # schedulers diverge (set distinct TPU seeds per replica
            # group for cross-replica variety).
            import random as _random

            self._seed_rng = (
                _random.Random(seed + 3) if multiproc
                else _random.Random(os.urandom(16))
            )
            self._active_dev = self._up(np.zeros((n_slots,), dtype=bool))
            self._temps_dev = self._up(np.ones((n_slots,), dtype=np.float32))
            self._topp_dev = self._up(np.ones((n_slots,), dtype=np.float32))
            self._greedy_dev = self._up(np.ones((n_slots,), dtype=bool))
            # Penalties state: per-slot generated-token counts (a [1]-wide
            # dummy when the feature is compiled out keeps one signature).
            pv = self.cfg.vocab_size if self.enable_penalties else 1
            self._pcounts_dev = self._up(
                np.zeros((n_slots, pv), dtype=np.int32)
            )
            self._fpen_dev = self._up(np.zeros((n_slots,), dtype=np.float32))
            self._ppen_dev = self._up(np.zeros((n_slots,), dtype=np.float32))
            self._bidx_host = np.full(
                (n_slots, LOGIT_BIAS_K), -1, dtype=np.int32
            )
            self._bval_host = np.zeros(
                (n_slots, LOGIT_BIAS_K), dtype=np.float32
            )
            self._bidx_dev = self._up(self._bidx_host)
            self._bval_dev = self._up(self._bval_host)
            tlk = max(1, self.top_logprobs)
            self._topi_dev = self._up(
                np.zeros((n_slots, tlk), dtype=np.int32)
            )
            self._topl_dev = self._up(
                np.zeros((n_slots, tlk), dtype=np.float32)
            )
            self._slot_state_dirty = True
            # Token history per slot (prompt + generated) — the n-gram
            # draft source; only maintained when speculation is on.
            self._history_dev = (
                self._up(np.zeros((n_slots, self.max_len), dtype=np.int32))
                if self.spec_tokens else None
            )
            # Multi-LoRA serving: merge zeroed stacked adapter leaves
            # into params["layers"] (slot 0 = base; load_lora fills
            # slots 1..lora_slots). A COMPILE choice: engines without
            # TPU_LORA_SLOTS carry no adapter gather/einsums at all.
            self.lora_slots = max(0, lora_slots)
            self.lora_rank = max(1, lora_rank)
            self._lora_targets = tuple(
                t.strip() for t in lora_targets.split(",") if t.strip()
            )
            self._lora_names: dict[str, int] = {}
            # Per-adapter-slot load generation: bumped by every load/
            # unload so in-flight prefix registrations against an old
            # generation can be detected and dropped.
            self._lora_gen = [0] * (self.lora_slots + 1)
            if self.lora_slots:
                from gofr_tpu.models.transformer import (
                    init_lora,
                    lora_param_specs,
                )

                leaves = init_lora(
                    self.cfg, 1 + self.lora_slots, self.lora_rank,
                    self._lora_targets,
                )
                if mesh is not None:
                    from gofr_tpu.parallel.sharding import (
                        named_shardings,
                        prune_specs,
                    )

                    lspecs = prune_specs(
                        lora_param_specs(self._lora_targets), mesh
                    )
                    leaves = {
                        k: jax.device_put(
                            v, named_shardings(lspecs[k], mesh)
                        )
                        for k, v in leaves.items()
                    }
                self.params = {
                    **self.params,
                    "layers": {**self.params["layers"], **leaves},
                }
            self._build_llm_steps()
        elif self.family == "encoder":
            self.max_len = min(max_len, self.cfg.max_len)
            self._build_encoder_step()
            self._batcher = DynamicBatcher(
                self._execute_embed, max_batch=max_batch, max_wait_s=max_wait_s,
                metrics=metrics, name="embed",
            )
        elif self.family == "vision":
            self._build_vision_step()
            self._batcher = DynamicBatcher(
                self._execute_classify, max_batch=max_batch, max_wait_s=max_wait_s,
                metrics=metrics, name="classify",
            )
        elif self.family == "seq2seq":
            self.max_len = min(max_len, self.cfg.max_len)
            self._build_seq2seq_step()
            self._batcher = DynamicBatcher(
                self._execute_seq2seq, max_batch=max_batch,
                max_wait_s=max_wait_s, metrics=metrics, name="seq2seq",
            )
        else:
            raise ValueError(f"unknown model family {self.family}")

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------

    @classmethod
    def from_config(cls, config, logger=None, metrics=None) -> "InferenceEngine":
        """Container seam: all knobs are TPU_* env keys (the datasource
        config idiom, reference ``sql/sql.go:109-118``).

        ``TPU_MESH_TP=N`` serves tensor-parallel over N chips (ICI): params
        Megatron-sharded, KV heads sharded, XLA inserts the collectives.
        Data-parallel serving scale-out is engine replicas behind the
        service tier (the DCN story, SURVEY §2.6), not a mesh axis here.
        """
        mesh = None
        tp = int(config.get_or_default("TPU_MESH_TP", "1"))
        # Serving context parallelism: the KV cache's length axis shards
        # over cp chips, so max_len can exceed one chip's cache HBM
        # (GSPMD turns the sharded softmax reductions into collectives).
        cp = int(config.get_or_default("TPU_MESH_CP", "1"))
        if tp > 1 or cp > 1:
            from gofr_tpu.parallel import make_mesh

            axes = {}
            if tp > 1:
                axes["tp"] = tp
            if cp > 1:
                axes["cp"] = cp
            mesh = make_mesh(axes)
        model_name = config.get_or_default("TPU_MODEL", "llama-tiny")
        ckpt = config.get_or_default("TPU_CHECKPOINT", "")
        quant_cfg = config.get_or_default("TPU_QUANT", "")
        params = None
        if ckpt:
            from gofr_tpu.serving.hf_loader import (
                is_hf_checkpoint,
                load_hf_llama,
            )

            if is_hf_checkpoint(ckpt):
                # Real weights (HF safetensors layout), quantized leaf-wise
                # on device as they land — the bf16 tree never fully
                # materializes (VERDICT r1 #5 + #4) — and placed straight
                # onto the tp mesh when one is configured.
                from gofr_tpu.models.registry import get_model

                spec = get_model(model_name)
                if spec.family == "seq2seq":
                    from gofr_tpu.models.t5 import load_hf_t5

                    if mesh is not None:
                        # Silently serving replicated would defeat the
                        # operator's explicit parallelism settings.
                        raise ValueError(
                            "TPU_MESH_* is not supported for seq2seq "
                            "checkpoints yet"
                        )
                    params = load_hf_t5(
                        ckpt, spec.config, quant=quant_cfg
                    )
                else:
                    params = load_hf_llama(
                        ckpt, spec.config, quant=quant_cfg,
                        mesh=mesh, logger=logger,
                    )
        engine = cls(
            model_name,
            mesh=mesh,
            params=params,
            quant="" if (params is not None or ckpt) else quant_cfg,
            n_slots=int(config.get_or_default("TPU_KV_SLOTS", "8")),
            max_len=int(config.get_or_default("TPU_MAX_LEN", "1024")),
            max_batch=int(config.get_or_default("TPU_MAX_BATCH", "8")),
            max_wait_s=float(config.get_or_default("TPU_BATCH_WAIT_MS", "5")) / 1e3,
            window_k=int(config.get_or_default("TPU_DECODE_WINDOW", "8")),
            pipeline_depth=int(config.get_or_default("TPU_PIPELINE_DEPTH", "2")),
            mega_windows=int(config.get_or_default("TPU_MEGA_WINDOWS", "0")),
            prefill_depth=int(config.get_or_default("TPU_PREFILL_DEPTH", "1")),
            kv_quant=config.get_or_default("TPU_KV_QUANT", ""),
            prefix_slots=int(config.get_or_default("TPU_PREFIX_SLOTS", "0")),
            prefill_chunk=int(config.get_or_default("TPU_PREFILL_CHUNK", "256")),
            prefill_batch=int(config.get_or_default("TPU_PREFILL_BATCH", "8")),
            truncate_prompts=config.get_or_default(
                "TPU_TRUNCATE_PROMPTS", "false"
            ).lower() in ("1", "true", "yes"),
            top_k=int(config.get_or_default("TPU_TOP_K", "0")),
            top_logprobs=int(config.get_or_default("TPU_TOP_LOGPROBS", "0")),
            enable_top_p=config.get_or_default("TPU_TOP_P", "false").lower()
            in ("1", "true", "yes"),
            enable_penalties=config.get_or_default(
                "TPU_PENALTIES", "false"
            ).lower() in ("1", "true", "yes"),
            spec_tokens=int(config.get_or_default("TPU_SPEC_TOKENS", "0")),
            kv_block=int(config.get_or_default("TPU_KV_BLOCK", "0")),
            lora_slots=int(config.get_or_default("TPU_LORA_SLOTS", "0")),
            lora_rank=int(config.get_or_default("TPU_LORA_RANK", "16")),
            lora_targets=config.get_or_default(
                "TPU_LORA_TARGETS", "wq,wk,wv,wo"
            ),
            kv_pool_blocks=int(
                config.get_or_default("TPU_KV_POOL_BLOCKS", "0")
            ),
            logger=logger,
            metrics=metrics,
            tokenizer=tokenizer_from_config(config, logger),
        )
        if ckpt and params is None:
            # Orbax checkpoint path: restore bf16 params, then quantize.
            from gofr_tpu.serving.checkpoint import maybe_restore_params

            engine.params = maybe_restore_params(config, engine.params, logger)
            engine.apply_quantization(quant_cfg)
        # Boot-time LoRA adapters: TPU_LORA_ADAPTERS="name=path,name2=p2"
        # (HF PEFT checkpoint dirs). More can load at runtime via
        # engine.load_lora.
        adapters_cfg = config.get_or_default("TPU_LORA_ADAPTERS", "")
        if adapters_cfg:
            for entry in adapters_cfg.replace(";", ",").split(","):
                entry = entry.strip()
                if not entry:
                    continue
                if "=" not in entry:
                    raise ValueError(
                        f"TPU_LORA_ADAPTERS entry {entry!r} is not "
                        f"name=path"
                    )
                name, path = entry.split("=", 1)
                engine.load_lora(name.strip(), path.strip())
        return engine

    def _init_llm_quantized(self, seed: int) -> dict:
        """Random-init the transformer leaf-by-leaf with immediate int8 or
        int4 quantization (``self.quant``) of the matmul weights (same
        fan-in-scaled normal as ``init_transformer``, different key-split
        order — irrelevant for random weights). Each leaf's bf16 tensor is
        transient inside its own jit, so an 8B tree peaks near its
        quantized footprint."""
        jax, jnp = self._jax, self._jnp
        from gofr_tpu.ops.quant import (
            _QUANT_KEYS,
            quantize_array,
            quantize_array4,
        )

        quantize_leaf = (
            quantize_array4 if self.quant == "int4" else quantize_array
        )

        cfg = self.cfg
        shapes = jax.eval_shape(
            lambda k: self.spec.init(k, cfg), jax.random.PRNGKey(0)
        )
        base = jax.random.PRNGKey(seed)
        counter = [0]

        def make(name: str, sds):
            counter[0] += 1
            key = jax.random.fold_in(base, counter[0])
            if name in ("attn_norm", "mlp_norm", "final_norm"):
                # (1+w) norm models (Gemma) use zeros as identity.
                return jnp.full(
                    sds.shape, 0.0 if cfg.norm_offset else 1.0, cfg.dtype
                )
            if name.endswith("_b"):  # QKV biases: zeros, as init_transformer
                return jnp.zeros(sds.shape, cfg.dtype)
            fan_in = sds.shape[-1] if name == "embed" else sds.shape[-2]

            def init_leaf(k):
                w = (
                    jax.random.normal(k, sds.shape, jnp.float32) * fan_in**-0.5
                ).astype(cfg.dtype)
                return quantize_leaf(w) if name in _QUANT_KEYS else w

            return jax.jit(init_leaf)(key)

        return {
            "embed": make("embed", shapes["embed"]),
            "layers": {
                k: make(k, v) for k, v in shapes["layers"].items()
            },
            "final_norm": make("final_norm", shapes["final_norm"]),
            "lm_head": make("lm_head", shapes["lm_head"]),
        }

    def _build_llm_steps(self) -> None:
        jax, jnp = self._jax, self._jnp
        from gofr_tpu.models.transformer import (
            transformer_decode_step,
            transformer_prefill_chunk,
        )
        cfg, top_k = self.cfg, self._top_k
        # pallas kernels don't auto-partition under GSPMD: mesh-sharded
        # serving takes the dense attention formulations, which XLA
        # partitions (per-head locality under tp; sharded-softmax
        # collectives under cp).
        dense_attn = self.mesh is not None

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            _rep_sh = NamedSharding(self.mesh, PartitionSpec())

            def rep(x):
                # Host-fetched outputs must be REPLICATED: on a multi-host
                # (DCN) mesh every process np.asarray()s its local shard,
                # which is only the full value if the sharding says so.
                return jax.lax.with_sharding_constraint(x, _rep_sh)
        else:
            def rep(x):
                return x

        enable_top_p = self.enable_top_p
        enable_penalties = self.enable_penalties
        top_lp_k = self.top_logprobs

        def sample(logits, keys, temps, greedy, topps, pen=None,
                   bias=None):
            """Returns (token, logprob) — the logprob is the log-softmax at
            the chosen token of the distribution the choice was made from
            (the model's own when no penalties apply), the number the
            OpenAI logprobs field reports.

            pen: optional (counts [rows, V] int32, fpen [rows], ppen
            [rows]) — OpenAI-style frequency/presence penalties over the
            GENERATED tokens (prompt tokens don't count, the vLLM
            convention), applied before greedy argmax AND sampling so
            temperature-0 requests honor them too."""
            logits = logits.astype(jnp.float32)
            if bias is not None:
                # OpenAI logit_bias: sparse per-request (token, bias)
                # pairs, padded with idx -1. Applied to the raw logits —
                # before penalties, greedy argmax, and sampling.
                bidx, bval = bias
                rows = jnp.arange(logits.shape[0])[:, None]
                logits = logits.at[rows, jnp.clip(bidx, 0)].add(
                    jnp.where(bidx >= 0, bval, 0.0)
                )
            if pen is not None:
                counts, fpen, ppen = pen
                cf = counts.astype(jnp.float32)
                logits = (
                    logits
                    - fpen[:, None] * cf
                    - ppen[:, None] * (cf > 0).astype(jnp.float32)
                )
            greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temps, 1e-4)[:, None]
            sorted_l = None
            if top_k > 0:
                sorted_l = jnp.sort(scaled, axis=-1)[:, ::-1]
                kth = sorted_l[:, top_k - 1][:, None]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            if enable_top_p:
                # Per-slot nucleus: keep the smallest prefix of the
                # sorted distribution with cumulative prob >= top_p
                # (slots at top_p=1.0 are untouched).
                if sorted_l is not None:
                    # Post-top_k sorted logits are the already-sorted
                    # list with positions >= top_k masked — no second
                    # vocab-wide sort on the decode hot path.
                    V = sorted_l.shape[-1]
                    sorted_p = jnp.where(
                        jnp.arange(V)[None, :] < top_k, sorted_l, -jnp.inf
                    )
                else:
                    sorted_p = jnp.sort(scaled, axis=-1)[:, ::-1]
                cum = jnp.cumsum(jax.nn.softmax(sorted_p, axis=-1), axis=-1)
                # Guarantee the predicate holds somewhere: fp32 cumsum
                # over a big vocab can top out just below a top_p≈1,
                # and argmax over all-False would return 0 — silently
                # collapsing the request to greedy.
                cum = cum.at[:, -1].set(2.0)
                cut_idx = jnp.argmax(cum >= topps[:, None], axis=-1)
                cutoff = jnp.take_along_axis(
                    sorted_p, cut_idx[:, None], axis=-1
                )
                scaled = jnp.where(
                    (topps < 1.0)[:, None] & (scaled < cutoff),
                    -jnp.inf, scaled,
                )
            sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(
                jnp.int32
            )
            chosen = jnp.where(greedy, greedy_tok, sampled)
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            logp = jnp.take_along_axis(logp_all, chosen[:, None], axis=-1)[:, 0]
            if top_lp_k:
                # OpenAI top_logprobs alternatives, from the same
                # (biased/penalized) distribution the choice used.
                tl, ti = jax.lax.top_k(logp_all, top_lp_k)
                return chosen, logp, ti.astype(jnp.int32), tl
            return chosen, logp, None, None

        # Per-request reproducible sampling: each sampled token's key is
        # fold_in(fold_in(engine_base, request_seed), n_sampled_so_far) —
        # counter-based, so a seeded stream is identical regardless of
        # batch composition, window size, or mega/pipelined scheduling.
        base_key = jax.random.PRNGKey(self._seed + 2)

        def row_keys(seeds, nsteps):
            def one(sd, n):
                return jax.random.fold_in(
                    jax.random.fold_in(base_key, sd), n
                )

            return jax.vmap(one)(seeds, nsteps)

        def _prefill_core(
            params, cache, tokens, slots, starts, lens, finalize, row_valid,
            temps, greedy, topps, seeds, all_tokens, all_logps, pcounts,
            nsteps, bidx, bval, topi, topl, aids, use_bias,
        ):
            """One [P, c] chunk: write K/V + attend; on rows whose prompt
            finishes (finalize) sample the first token and merge it into
            the decode token vector ON DEVICE. Padding rows duplicate row 0
            (identical K/V writes are idempotent; the merge below is
            per-slot select, not scatter, so duplicates can't race).
            pcounts: per-slot generated-token counts (penalties feature) —
            finalize RESETS the slot's row (new request) and counts the
            first sampled token; the first token itself is never penalized
            (its counts are the zeros just written)."""
            logits, cache = transformer_prefill_chunk(
                params, tokens, cache, slots, starts, lens, cfg,
                dense_attn=dense_attn, aids=aids[slots],
            )
            sub = row_keys(seeds[slots], jnp.zeros_like(slots))
            first, first_lp, ftopi, ftopl = sample(
                logits, sub, temps, greedy, topps,
                bias=(bidx[slots], bval[slots]) if use_bias else None,
            )
            S = all_tokens.shape[0]
            match = (
                (jnp.arange(S)[:, None] == slots[None, :])
                & finalize[None, :] & row_valid[None, :]
            )  # [S, P]
            has = jnp.any(match, axis=1)
            idx = jnp.argmax(match, axis=1)
            all_tokens = jnp.where(has, first[idx], all_tokens)
            all_logps = jnp.where(has, first_lp[idx], all_logps)
            cache = cache._replace(
                lengths=jnp.where(has, (starts + lens)[idx], cache.lengths)
            )
            if enable_penalties:
                pcounts = jnp.where(has[:, None], 0, pcounts)
                pcounts = pcounts.at[
                    jnp.arange(S), all_tokens
                ].add(has.astype(jnp.int32))
            # The first token was sampled with n=0; the slot's next sample
            # uses n=1.
            nsteps = jnp.where(has, 1, nsteps)
            if top_lp_k:
                topi = jnp.where(has[:, None], ftopi[idx], topi)
                topl = jnp.where(has[:, None], ftopl[idx], topl)
                return (cache, all_tokens, all_logps, rep(first),
                        rep(first_lp), pcounts, nsteps, topi, topl,
                        rep(ftopi), rep(ftopl))
            return (cache, all_tokens, all_logps, rep(first), rep(first_lp),
                    pcounts, nsteps, topi, topl, None, None)

        prefill_chunk_step = partial(
            jax.jit, donate_argnums=(1, 12, 13, 14, 15, 18, 19),
            static_argnames=("use_bias",),
        )(_prefill_core)

        def _multi_chunk_core(params, cache, tokens3, slots, starts0,
                              n_chunks, history, aids):
            """Up to D FULL (non-finalizing) [P, c] chunks in ONE dispatch
            — the long-prompt TTFT amortizer: through a network-attached
            relay every chunk dispatch costs a host↔device RTT, so an 8k
            prompt at c=256 pays ~32 RTTs (~2.3 s) without this. No
            sampling and no lengths update happen here (both belong to
            the finalize chunk, which always runs via the single-chunk
            step); history recording (speculation) mirrors
            prefill_chunk_step_hist. tokens3: [D, P, c]; n_chunks ≤ D is
            a runtime operand, so one compile serves every prompt length."""
            D, Pb, c = tokens3.shape

            def cond(s):
                return s[0] < n_chunks

            def body(s):
                i, cache, history = s
                toks = jax.lax.dynamic_index_in_dim(
                    tokens3, i, 0, keepdims=False
                )
                starts = starts0 + i * c
                lens = jnp.full((Pb,), c, jnp.int32)
                _, cache = transformer_prefill_chunk(
                    params, toks, cache, slots, starts, lens, cfg,
                    dense_attn=dense_attn, aids=aids[slots],
                )
                if history is not None:
                    hpos = jnp.clip(
                        starts[:, None] + jnp.arange(c)[None, :], 0,
                        history.shape[1] - 1,
                    )
                    history = history.at[slots[:, None], hpos].set(toks)
                return i + 1, cache, history

            _, cache, history = jax.lax.while_loop(
                cond, body, (jnp.asarray(0, jnp.int32), cache, history)
            )
            return cache, history

        @partial(jax.jit, donate_argnums=(1,))
        def prefill_multi_chunk(params, cache, tokens3, slots, starts0,
                                n_chunks, aids):
            cache, _ = _multi_chunk_core(
                params, cache, tokens3, slots, starts0, n_chunks, None, aids
            )
            return cache

        @partial(jax.jit, donate_argnums=(1, 6))
        def prefill_multi_chunk_hist(params, cache, tokens3, slots, starts0,
                                     n_chunks, history, aids):
            return _multi_chunk_core(
                params, cache, tokens3, slots, starts0, n_chunks, history,
                aids,
            )

        @partial(
            jax.jit, donate_argnums=(1, 12, 13, 14, 15, 18, 19, 21),
            static_argnames=("use_bias",),
        )
        def prefill_chunk_step_hist(
            params, cache, tokens, slots, starts, lens, finalize, row_valid,
            temps, greedy, topps, seeds, all_tokens, all_logps, pcounts,
            nsteps, bidx, bval, topi, topl, aids, history, use_bias=False,
        ):
            """Prefill + record the chunk's tokens into the draft history
            (speculation on). Padding rows duplicate row 0 — idempotent."""
            out = _prefill_core(
                params, cache, tokens, slots, starts, lens, finalize,
                row_valid, temps, greedy, topps, seeds, all_tokens,
                all_logps, pcounts, nsteps, bidx, bval, topi, topl, aids,
                use_bias,
            )
            c = tokens.shape[1]
            hpos = jnp.clip(
                starts[:, None] + jnp.arange(c)[None, :], 0,
                history.shape[1] - 1,
            )
            history = history.at[slots[:, None], hpos].set(tokens)
            return out + (history,)

        def make_decode_body(params, active, temps, greedy, topps, fpen,
                             ppen, seeds, bidx, bval, use_bias, aids):
            """One decode step (scan body): forward + sample + penalty
            count scatter — shared by the plain window and the mega
            while_loop so the two dispatch modes cannot drift."""

            def body(carry, _):
                tokens, logps, cache, nsteps, pcounts, topi, topl = carry
                logits, cache = transformer_decode_step(
                    params, tokens, cache, active, cfg,
                    dense_attn=dense_attn, aids=aids,
                )
                pen = (pcounts, fpen, ppen) if enable_penalties else None
                sub = row_keys(seeds, nsteps)
                nxt, nlp, ntopi, ntopl = sample(
                    logits, sub, temps, greedy, topps, pen,
                    bias=(bidx, bval) if use_bias else None,
                )
                nsteps = nsteps + active.astype(jnp.int32)
                if enable_penalties:
                    pcounts = pcounts.at[
                        jnp.arange(nxt.shape[0]), nxt
                    ].add(active.astype(jnp.int32))
                # Alternatives travel WITH their token: the carried planes
                # belong to the token entering this step (ys), the fresh
                # ones to the token just chosen (next carry).
                ys = (tokens, logps, topi, topl) if top_lp_k else (
                    tokens, logps
                )
                if not top_lp_k:
                    ntopi, ntopl = topi, topl
                return (nxt, nlp, cache, nsteps, pcounts, ntopi, ntopl), ys

            return body

        @partial(
            jax.jit, static_argnames=("k", "use_bias"),
            donate_argnums=(3, 5, 11, 15, 16),
        )
        def decode_window(params, tokens, logps, cache, active, nsteps,
                          temps, greedy, topps, fpen, ppen, pcounts, seeds,
                          bidx, bval, topi, topl, aids, k, use_bias):
            """Run k decode steps entirely on device; emit the k
            (token, logprob) pairs that ENTER each step (so a freshly
            prefilled slot's first token is emitted by its first window)
            and carry the (k+1)-th as next input. One host fetch per k
            tokens — emitted tokens and logprobs pack into ONE [2, k, S]
            f32 block (token ids are exact in f32 below 2^24) so the
            host↔device roundtrip count stays one per window. Sampling
            keys are counter-based — nsteps threads through ON DEVICE and
            the seeds plane uploads only on admission — so steady-state
            dispatch uploads nothing host→device at all."""
            body = make_decode_body(params, active, temps, greedy, topps,
                                    fpen, ppen, seeds, bidx, bval, use_bias,
                                    aids)
            (final, final_lp, cache, nsteps, pcounts, topi, topl), ys = (
                jax.lax.scan(
                    body,
                    (tokens, logps, cache, nsteps, pcounts, topi, topl),
                    length=k,
                )
            )
            if top_lp_k:
                etoks, elps, etopi, etopl = ys
                etops = rep(jnp.stack([etopi.astype(jnp.float32), etopl]))
            else:
                etoks, elps = ys
                etops = None
            emitted = jnp.stack([etoks.astype(jnp.float32), elps])
            return (rep(emitted), etops, final, final_lp, cache, nsteps,
                    pcounts, topi, topl)

        eos_id = self.tokenizer.eos_id if self.tokenizer is not None else -1

        @partial(
            jax.jit, static_argnames=("k", "m", "use_bias"),
            donate_argnums=(3, 5, 11, 15, 16),
        )
        def mega_window(params, tokens, logps, cache, active, nsteps, temps,
                        greedy, topps, fpen, ppen, pcounts, seeds, bidx,
                        bval, topi, topl, remaining, eos_stop, aids, k, m,
                        use_bias):
            """Up to m k-step windows in ONE dispatch. A device-side
            while_loop runs windows until every slot's `remaining` budget
            is covered (decremented k per window; zeroed when the slot
            emits EOS and `eos_stop` holds) or m windows have run. Emits
            into a fixed [2, m*k, S] buffer; entries past the returned
            windows_run*k are untouched zeros the host must not read.
            Slots whose budget ran out while others continue keep
            computing junk tokens — their cache writes land past their
            retired region (scatter drops OOB; paged lookups park at
            block 0) and the host drops the tokens post-retirement, so
            the junk is slot-local by construction."""
            body = make_decode_body(params, active, temps, greedy, topps,
                                    fpen, ppen, seeds, bidx, bval, use_bias,
                                    aids)
            S = tokens.shape[0]
            emitted0 = jnp.zeros((2, m * k, S), dtype=jnp.float32)
            etops0 = (
                jnp.zeros((2, m * k, S, top_lp_k), dtype=jnp.float32)
                if top_lp_k else jnp.zeros((0,), dtype=jnp.float32)
            )

            def win_body(state):
                (w, tokens, logps, cache, nsteps, pcounts, remaining,
                 emitted, etops, topi, topl) = state
                ((tokens, logps, cache, nsteps, pcounts, topi, topl),
                 ys) = jax.lax.scan(
                    body,
                    (tokens, logps, cache, nsteps, pcounts, topi, topl),
                    length=k,
                )
                if top_lp_k:
                    etoks, elps, etopi, etopl = ys
                    etops = jax.lax.dynamic_update_slice(
                        etops,
                        jnp.stack([etopi.astype(jnp.float32), etopl]),
                        (0, w * k, 0, 0),
                    )
                else:
                    etoks, elps = ys
                slab = jnp.stack([etoks.astype(jnp.float32), elps])
                emitted = jax.lax.dynamic_update_slice(
                    emitted, slab, (0, w * k, 0)
                )
                hit = jnp.any(etoks == eos_id, axis=0) & eos_stop
                remaining = jnp.where(hit, 0, jnp.maximum(remaining - k, 0))
                return (w + 1, tokens, logps, cache, nsteps, pcounts,
                        remaining, emitted, etops, topi, topl)

            def win_cond(state):
                return (state[0] < m) & jnp.any(state[6] > 0)

            (w, final, final_lp, cache, nsteps, pcounts, _, emitted, etops,
             topi, topl) = jax.lax.while_loop(
                win_cond, win_body,
                (jnp.asarray(0, jnp.int32), tokens, logps, cache,
                 nsteps, pcounts, remaining, emitted0, etops0, topi, topl),
            )
            return (rep(emitted), rep(etops) if top_lp_k else None, rep(w),
                    final, final_lp, cache, nsteps, pcounts, topi, topl)

        G = self.spec_tokens

        def make_spec_body(params, active, temps, greedy, topps, seeds,
                           aids):
            """One speculative step (scan body), shared by the plain spec
            window and the mega-spec while_loop."""
            from gofr_tpu.models.transformer import (
                commit_chunk_kv,
                ngram_draft,
                transformer_verify_step,
            )

            def body(carry, _):
                tokens, logps, cache, nsteps, history = carry
                sub = row_keys(seeds, nsteps)
                draft = ngram_draft(history, cache.lengths, tokens, G)
                inputs = jnp.concatenate([tokens[:, None], draft], axis=1)
                logits, nk, nv = transformer_verify_step(
                    params, inputs, cache, cfg, aids=aids
                )
                greedy_next = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                samp0, samp0_lp, _, _ = sample(
                    logits[:, 0], sub, temps, greedy, topps
                )
                match = draft == greedy_next[:, :G]
                acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
                acc = jnp.where(greedy, acc, 0)  # sampled slots: no drafts
                bonus_g = jnp.take_along_axis(
                    greedy_next, acc[:, None], axis=1
                )[:, 0]
                bonus = jnp.where(greedy, bonus_g, samp0)
                logp_all = jax.nn.log_softmax(logits, axis=-1)
                draft_lp = jnp.take_along_axis(
                    logp_all[:, :G], draft[..., None], axis=2
                )[..., 0]  # [S, G]
                pos_lp = jnp.take_along_axis(
                    logp_all, acc[:, None, None], axis=1
                )[:, 0]  # [S, V] — distribution at the bonus position
                bonus_lp = jnp.where(
                    greedy,
                    jnp.take_along_axis(pos_lp, bonus_g[:, None], axis=1)[:, 0],
                    samp0_lp,
                )
                counts = jnp.where(active, acc + 1, 0)
                step_tokens = inputs  # [S, G+1]; first `counts` are emitted
                step_logps = jnp.concatenate(
                    [logps[:, None], draft_lp], axis=1
                )
                cache = commit_chunk_kv(cache, nk, nv, active, cfg)
                # History: current+accepted drafts at len..len+acc, bonus at
                # len+counts — the invariant "current token sits at
                # history[lengths]" holds into the next step. Rejected
                # drafts and inactive slots park at max_len-1 (XLA scatter
                # is nondeterministic on duplicate indices, so the rejected
                # entries must not share a position with the bonus write;
                # history[max_len-1] garbage only ever wastes a draft).
                S2, T = history.shape
                hvals = jnp.concatenate([inputs, bonus[:, None]], axis=1)
                hpos = cache.lengths[:, None] + jnp.arange(G + 2)[None, :]
                hpos = hpos.at[:, G + 1].set(cache.lengths + counts)
                keep = jnp.concatenate(
                    [
                        jnp.arange(G + 1)[None, :] <= acc[:, None],
                        jnp.ones((S2, 1), dtype=bool),
                    ],
                    axis=1,
                )
                keep = keep & active[:, None]
                hpos = jnp.where(keep, jnp.minimum(hpos, T - 1), T - 1)
                history = history.at[
                    jnp.arange(S2)[:, None], hpos
                ].set(hvals)
                cache = cache._replace(lengths=cache.lengths + counts)
                nsteps = nsteps + counts
                return (
                    (bonus, bonus_lp, cache, nsteps, history),
                    (step_tokens, step_logps, counts),
                )

            return body

        @partial(
            jax.jit, static_argnames=("k",), donate_argnums=(3, 5, 9)
        )
        def spec_window(params, tokens, logps, cache, active, nsteps, temps,
                        greedy, topps, history, seeds, aids, k):
            """k speculative steps on device. Each step drafts G tokens by
            n-gram lookup in the slot's own history, verifies draft+current
            in ONE [S, G+1] forward (cache read-only), accepts the longest
            matching prefix (greedy slots — lossless by construction;
            sampled slots take 0 drafts and resample position 0), commits
            all layers' K/V in one scatter, and carries the bonus token.
            Emits per step: tokens [S, G+1] (= the step's inputs), logps,
            and counts [S] (=accepted+1 valid entries)."""
            body = make_spec_body(params, active, temps, greedy, topps,
                                  seeds, aids)
            ((final, final_lp, cache, nsteps, history),
             (etoks, elps, ecnt)) = jax.lax.scan(
                body, (tokens, logps, cache, nsteps, history), length=k
            )
            emitted = jnp.stack(
                [etoks.astype(jnp.float32), elps]
            )  # [2, k, S, G+1]
            return (rep(emitted), rep(ecnt), final, final_lp, cache, nsteps,
                    history)

        @partial(
            jax.jit, static_argnames=("k", "m"), donate_argnums=(3, 5, 9)
        )
        def mega_spec_window(params, tokens, logps, cache, active, nsteps,
                             temps, greedy, topps, history, seeds, remaining,
                             eos_stop, aids, k, m):
            """Mega × speculation: up to m k-step spec windows in ONE
            dispatch. `remaining` decrements by the ACTUAL emitted token
            counts (speculation emits ≥ k per window per live slot, so
            coverage ≥ the plain-decode guarantee); EOS detection scans
            only the VALID (first `counts`) entries of each step —
            rejected draft positions must not zero a budget."""
            body = make_spec_body(params, active, temps, greedy, topps,
                                  seeds, aids)
            S = tokens.shape[0]
            emitted0 = jnp.zeros((2, m * k, S, G + 1), dtype=jnp.float32)
            ecnt0 = jnp.zeros((m * k, S), dtype=jnp.int32)

            def win_body(state):
                (w, tokens, logps, cache, nsteps, history, remaining,
                 emitted, ecnt) = state
                ((tokens, logps, cache, nsteps, history),
                 (etoks, elps, cnts)) = jax.lax.scan(
                    body, (tokens, logps, cache, nsteps, history), length=k
                )
                slab = jnp.stack([etoks.astype(jnp.float32), elps])
                emitted = jax.lax.dynamic_update_slice(
                    emitted, slab, (0, w * k, 0, 0)
                )
                ecnt = jax.lax.dynamic_update_slice(
                    ecnt, cnts.astype(jnp.int32), (w * k, 0)
                )
                valid = (
                    jnp.arange(G + 1)[None, None, :] < cnts[:, :, None]
                )  # [k, S, G+1]
                hit = (
                    ((etoks == eos_id) & valid).any(axis=(0, 2)) & eos_stop
                )
                delivered = cnts.sum(axis=0).astype(jnp.int32)  # [S]
                remaining = jnp.where(
                    hit, 0, jnp.maximum(remaining - delivered, 0)
                )
                return (w + 1, tokens, logps, cache, nsteps, history,
                        remaining, emitted, ecnt)

            def win_cond(state):
                return (state[0] < m) & jnp.any(state[6] > 0)

            ((w, final, final_lp, cache, nsteps, history, _, emitted,
              ecnt)) = jax.lax.while_loop(
                win_cond, win_body,
                (jnp.asarray(0, jnp.int32), tokens, logps, cache, nsteps,
                 history, remaining, emitted0, ecnt0),
            )
            return (rep(emitted), rep(ecnt), rep(w), final, final_lp, cache,
                    nsteps, history)

        self._prefill_chunk_step = prefill_chunk_step
        self._prefill_chunk_step_hist = prefill_chunk_step_hist
        self._prefill_multi_chunk = prefill_multi_chunk
        self._prefill_multi_chunk_hist = prefill_multi_chunk_hist
        self._decode_window = decode_window
        self._mega_window = mega_window
        self._spec_window = spec_window
        self._mega_spec_window = mega_spec_window

    def _build_encoder_step(self) -> None:
        from gofr_tpu.models.bert import bert_embed

        cfg = self.cfg
        self._embed_step = self._jax.jit(
            lambda params, tokens, mask: bert_embed(params, tokens, mask, cfg)
        )

    def _build_seq2seq_step(self) -> None:
        from gofr_tpu.models.t5 import t5_generate

        cfg = self.cfg
        max_new = self._seq2seq_max_new = int(
            os.environ.get("TPU_SEQ2SEQ_MAX_NEW", "64")
        )
        eos = self.spec.eos_token
        self._seq2seq_step = self._jax.jit(
            lambda params, tokens, lengths: t5_generate(
                params, tokens, lengths, cfg, max_new=max_new, eos_id=eos
            )
        )

    def _build_vision_step(self) -> None:
        cfg = self.cfg
        fwd = self.spec.forward
        if fwd is None:
            raise ValueError(
                f"vision model {self.model_name} registered without a "
                f"forward fn (ModelSpec.forward)"
            )
        self._classify_step = self._jax.jit(
            lambda params, images: fwd(params, images, cfg)
        )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def apply_quantization(self, mode: str) -> None:
        """Quantize weights in place (call BEFORE start / after restore).

        Weight-only int8: halves the HBM weight stream that bounds decode
        throughput; dequant fuses into the matmuls (``transformer._wein``).
        """
        mode = (mode or "").lower()
        if not mode:
            return
        if self.quant:
            # Idempotency guard (ADVICE r1): re-quantizing Q8 leaves crashes
            # inside jit with an opaque AttributeError.
            if self.quant == mode:
                return
            raise RuntimeError(
                f"params already quantized as {self.quant!r}; cannot "
                f"re-quantize as {mode!r}"
            )
        if mode not in ("int8", "int4"):
            raise ValueError(
                f"unsupported quant mode {mode!r} (int8 or int4)"
            )
        if self.family not in ("llm", "seq2seq"):
            raise ValueError(
                "quantization supports llm and seq2seq models only"
            )
        if getattr(self, "_running", False):  # __init__ calls this pre-flags
            raise RuntimeError("quantize before starting the engine")
        if self.family == "seq2seq":
            if self.mesh is not None:
                raise ValueError(
                    "quantized seq2seq does not compose with a mesh yet"
                )
            from gofr_tpu.models.t5 import quantize_t5_params

            self.params = self._jax.jit(
                lambda p: quantize_t5_params(p, mode), donate_argnums=(0,)
            )(self.params)
            self.quant = mode
            return
        from gofr_tpu.ops.quant import quantize_params

        # donate: the bf16 tree frees leaf-by-leaf as the int8 tree
        # materializes — without it peak HBM is ~1.5× the bf16 tree.
        if self.mesh is not None:
            # Sharded quantization: each Q8 leaf gets out-shardings derived
            # from its weight's PartitionSpec (the scale shards with the
            # output-channel axis), so quantized serving composes with a tp
            # mesh instead of gathering anything onto one chip.
            from gofr_tpu.models.transformer import transformer_param_specs
            from gofr_tpu.ops.quant import quantized_param_specs
            from gofr_tpu.parallel.sharding import named_shardings, prune_specs

            specs = quantized_param_specs(
                prune_specs(transformer_param_specs(self.cfg), self.mesh),
                mode,
            )
            self.params = self._jax.jit(
                partial(quantize_params, mode=mode), donate_argnums=(0,),
                out_shardings=named_shardings(specs, self.mesh),
            )(self.params)
        else:
            self.params = self._jax.jit(
                partial(quantize_params, mode=mode), donate_argnums=(0,)
            )(self.params)
        self.quant = mode

    async def start(self) -> None:
        self.start_sync()

    def start_sync(self) -> None:
        if self._running:
            return
        if self.family == "llm" and self._sched is not None:
            # A crashed scheduler may still be mid-drain; let it finish
            # before resetting flags, or its trailing `_drained = True`
            # would permanently reject submissions on the restarted engine.
            self._sched.join(timeout=10)
            self._sched = None
        self._running = True
        self._drained = False
        self._draining = False
        self._fatal = None
        if self.family == "llm":
            self._sched = threading.Thread(
                target=self._scheduler_loop, name="tpu-scheduler", daemon=True
            )
            self._sched.start()
        else:
            self._batcher.start()

    async def stop(self, drain_s: float = 0.0) -> None:
        if drain_s > 0:
            await asyncio.get_running_loop().run_in_executor(
                None, partial(self.stop_sync, drain_s)
            )
        else:
            self.stop_sync()

    def stop_sync(self, drain_s: float = 0.0) -> None:
        """Stop the engine. ``drain_s > 0`` = GRACEFUL: new submissions
        get 503 while in-flight generations run to completion (up to the
        deadline) — a rolling restart should not fail live requests the
        way a hard stop's drain does."""
        if drain_s > 0 and self.family == "llm" and self._running:
            with self._submit_lock:
                self._draining = True
                self._sched_idle = False
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                # Only the scheduler may declare the engine idle (it does
                # so under the submit lock after verifying every queue and
                # slot is empty) — polling the structures from here would
                # race requests in transit between them.
                if self._sched_idle or not self._running:
                    break
                time.sleep(0.05)
        self._running = False
        if self.family == "llm":
            self._work.set()
            if self._sched is not None:
                self._sched.join(timeout=10)
                self._sched = None
        else:
            self._batcher.stop()

    def close(self) -> None:
        self.stop_sync()

    # ------------------------------------------------------------------
    # LLM scheduler (continuous batching)
    # ------------------------------------------------------------------

    def _scheduler_loop(self) -> None:
        error: BaseException | None = None
        # Windows are PIPELINED `pipeline_depth` deep: dispatch window n+D
        # before fetching window n's tokens. The ~66ms host↔device roundtrip
        # (network-attached relay) is latency, not bandwidth — overlapping
        # D fetches with compute takes llama-1b from 518 (serial) to 987
        # (D=1) tok/s/chip and beyond; the floor becomes device step time.
        from collections import deque

        inflight: deque = deque()  # _dispatch_window return tuples
        try:
            while self._running:
                # One chunk step per iteration, interleaved 1:1 with decode
                # windows: a long prompt's prefill proceeds in bounded slices
                # and never freezes active token streams (VERDICT r1 #9).
                progressed = self._dispatch_prefill_chunk()
                # Wave admission: on a cold start or a retirement wave the
                # 1:1 interleave would refill capacity one chunk per window
                # — at 64 slots that is ~15 windows of a mostly-idle device
                # (measured: the 64-slot bench lost ~2 s per wave to it).
                # While live streams fill under a quarter of the slots, the
                # marginal inter-token latency of another ~1-4 ms chunk step
                # is noise next to the idle capacity, so keep draining; past
                # that, protect the live streams' latency (1:1 again).
                if progressed:
                    while (
                        sum(1 for s in self._slots if s is not None) * 4
                        < self.n_slots
                        and self._dispatch_prefill_chunk()
                    ):
                        pass
                self._flush_prefill_emits()
                any_active = any(s is not None for s in self._slots)
                if not any_active and not inflight:
                    if not progressed and not self._prefill_emits:
                        # Publish "verifiably idle" under the submit lock:
                        # the graceful drain trusts this flag, and the
                        # lock means no submission can race past it.
                        with self._submit_lock:
                            if self._pending.empty() and not self._wait_kv:
                                self._sched_idle = True
                        self._work.wait(timeout=0.02)
                        self._work.clear()
                    continue
                self._sched_idle = False
                # Dispatch only while some active slot still has budget
                # beyond what in-flight windows already cover — a wave of
                # same-length requests otherwise ends with `depth` pure-
                # overshoot windows whose tokens are all discarded.
                # (tokens_in_flight counts the GUARANTEED k emissions per
                # window + the prefill token; emitted = in_flight - 1, so
                # dispatch while in_flight <= budget. eos/stop retirements
                # end earlier via processing; speculation only ever emits
                # MORE per window than the guarantee.)
                wants_more = any_active and any(
                    s is not None
                    and s.tokens_in_flight <= s.request.max_new_tokens
                    for s in self._slots
                )
                if wants_more:
                    inflight.append(self._dispatch_window())
                while len(inflight) > (self.pipeline_depth if wants_more else 0):
                    self._process_window(*inflight.popleft())
        except BaseException as exc:  # noqa: BLE001 — must not strand futures
            # A scheduler crash (e.g. a kernel that fails to compile on this
            # hardware) must fail every caller, not hang them until timeout.
            error = exc
            self._fatal = exc
            self._running = False
            if self._logger is not None:
                self._logger.errorf("engine scheduler died: %s", exc)
        # Drain: fail queued requests AND active slots so no awaiting caller
        # hangs on an unresolved future / unterminated stream. The submit
        # lock closes the race where a submitter enqueues between the
        # scheduler's exit and this drain.
        reason: BaseException = error or RuntimeError("engine stopped")

        def _fail(req) -> None:
            # done() + InvalidStateError guard: an async caller may have
            # cancelled the future already.
            try:
                if not req.future.done():
                    req.future.set_exception(reason)
            except Exception:  # noqa: BLE001 — cancelled concurrently
                pass
            req.stream.put(None)

        # Block on in-flight windows first: returning from stop with device
        # computations + async host copies still outstanding races
        # interpreter teardown (observed as a runtime-client thread panic
        # at exit).
        while inflight:
            emitted = inflight.popleft()[0]
            try:
                np.asarray(emitted)
            except Exception:  # noqa: BLE001 — device may already be down
                pass
        with self._submit_lock:
            self._drained = True
            while not self._pending.empty():
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                _fail(req)
        for i, seq in enumerate(self._slots):
            if seq is None:
                continue
            _fail(seq.request)
            self._release_slot(i)
        for slot, st in list(self._prefilling.items()):
            _fail(st.request)
            del self._prefilling[slot]
        while self._wait_kv:
            _fail(self._wait_kv.popleft())
        self._prefill_emits.clear()

    # ------------------------------------------------------------------
    # paged-KV block allocator (host side; kv_block > 0 only)
    # ------------------------------------------------------------------

    def _ensure_blocks(self, slot: int, tokens: int) -> bool:
        """Grow ``slot``'s allocation to cover ``tokens`` logical tokens.
        Returns False when the pool is exhausted (caller defers or fails)
        — rolling back any partial grab, so a waiting request can never
        strand blocks on an idle slot while live streams starve."""
        B = self.kv_block
        target = min(
            (min(tokens, self.max_len) + B - 1) // B,
            self._table_host.shape[1],
        )
        row = self._slot_blocks[slot]
        start_len = len(row)
        while len(row) < target:
            if not self._free_blocks:
                while len(row) > start_len:  # rollback the partial grab
                    blk = row.pop()
                    self._table_host[slot, len(row)] = 0
                    self._free_blocks.append(blk)
                return False
            blk = self._free_blocks.pop()
            self._table_host[slot, len(row)] = blk
            row.append(blk)
            self._table_dirty = True
        if self._metrics is not None and len(row) != start_len:
            self._metrics.set_gauge(
                "app_tpu_kv_blocks_free", len(self._free_blocks),
                "model", self.model_name,
            )
        return True

    def _release_slot(self, slot: int) -> None:
        """Free a slot and (paged mode) return its blocks to the pool."""
        self._slots[slot] = None
        self._slot_state_dirty = True
        if self.kv_block:
            row = self._slot_blocks[slot]
            if row:
                self._free_blocks.extend(row)
                self._slot_blocks[slot] = []
                self._table_host[slot, :] = 0
                self._table_dirty = True
            self._dispatched_tokens[slot] = 0
        if self._metrics is not None and self.kv_block:
            self._metrics.set_gauge(
                "app_tpu_kv_blocks_free", len(self._free_blocks),
                "model", self.model_name,
            )

    def _push_table(self) -> None:
        """Upload the block-table mirror if admission/top-up dirtied it."""
        if self.kv_block and self._table_dirty:
            self.cache = self.cache._replace(
                block_table=self._up(self._table_host)
            )
            self._table_dirty = False

    def _window_tokens(self) -> int:
        return self.window_k * (self.spec_tokens + 1)

    def _dispatch_prefill_chunk(self) -> bool:
        """Admit pending requests into free slots and dispatch ONE
        fixed-shape [prefill_batch, prefill_chunk] chunk step.

        Each row advances one slot's prompt by up to ``prefill_chunk``
        tokens; rows whose prompt completes sample their first token and
        merge it into the decode token vector ON DEVICE (no host roundtrip
        between prefill and decode). Returns True if a step was dispatched.
        """
        # Admission is host bookkeeping only — the device work is the
        # chunk steps that follow.
        free = [
            i for i, s in enumerate(self._slots)
            if s is None and i not in self._prefilling
        ]
        while free and (self._wait_kv or not self._pending.empty()):
            if self._wait_kv:
                req = self._wait_kv.popleft()
            else:
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
            if self.kv_block:
                # A request bigger than the ENTIRE pool can never be
                # admitted — fail it now instead of deadlocking the
                # admission queue behind it forever.
                B = self.kv_block
                need = (min(len(req.prompt_ids) + 1, self.max_len) + B - 1) // B
                if need > self.cache.n_blocks - 1:
                    if not req.future.done():
                        req.future.set_exception(RuntimeError(
                            f"prompt needs {need} KV blocks but the pool "
                            f"has {self.cache.n_blocks - 1}; raise "
                            f"TPU_KV_POOL_BLOCKS"
                        ))
                    req.stream.put(None)
                    continue
                # Cover the prompt + the first decode token now; windows
                # top up ahead of dispatch. Pool dry → hold the request
                # back (retirements will refill the free list).
                if not self._ensure_blocks(
                    free[0], len(req.prompt_ids) + 1
                ):
                    self._wait_kv.appendleft(req)
                    break
                self._dispatched_tokens[free[0]] = 0
            # Clamp generation budget so pipelined-window overshoot can't
            # overrun the cache (admission-time guard; see _dispatch_window).
            room = (
                self.max_len - 1 - len(req.prompt_ids)
                - (self.pipeline_depth + 1) * self.window_k
                * (self.spec_tokens + 1)
            )
            req.max_new_tokens = max(1, min(req.max_new_tokens, room))
            slot = free.pop(0)
            self._seeds_host[slot] = req.seed
            self._aids_host[slot] = req.aid
            self._bidx_host[slot, :] = -1
            self._bval_host[slot, :] = 0.0
            for j, (tok, bv) in enumerate(req.logit_bias.items()):
                self._bidx_host[slot, j] = tok
                self._bval_host[slot, j] = bv
            self._seeds_dirty = True
            state = _PrefillState(request=req)
            if self._prefix_pool is not None and not req.prefix_store:
                # Per-adapter pools: pooled K/V is a function of the
                # weights that prefilled it, so a request only reuses a
                # prefix registered under its OWN adapter.
                idx, plen = self._prefix_pool.lookup(req.prompt_ids, req.aid)
                if idx >= 0:
                    # Copy pooled KV rows in; prefill only the remainder.
                    # done < len(prompt) always, so the final chunk still
                    # runs and samples the first token (re-writing the
                    # boundary token's K/V is idempotent).
                    self.cache = self._prefix_pool.load(
                        self.cache, idx, slot, plen
                    )
                    state.done = min(plen, len(req.prompt_ids) - 1)
                    if self._metrics is not None:
                        self._metrics.increment_counter(
                            "app_tpu_prefix_hits", "model", self.model_name
                        )
            self._prefilling[slot] = state
        if not self._prefilling:
            return False
        if self._seeds_dirty:
            # Upload the admission-scoped planes BEFORE any dispatch —
            # the deep multi-chunk branch below reads _aids_dev, so a
            # flush only on the single-chunk path would prefill a long
            # prompt with the slot's PREVIOUS occupant's adapter.
            self._seeds_dev = self._up(self._seeds_host)
            self._bidx_dev = self._up(self._bidx_host)
            self._bval_dev = self._up(self._bval_host)
            self._aids_dev = self._up(self._aids_host)
            self._seeds_dirty = False

        P, c = self.prefill_batch, self.prefill_chunk
        rows = list(self._prefilling.items())[:P]

        # Multi-chunk fast path: rows with ≥2 full chunks before their
        # finalize chunk burn through up to prefill_depth of them in one
        # device-side loop (no sampling, no finalize — the single-chunk
        # step below always closes a prompt). Only DEEP rows join the
        # batch — one short prompt admitted alongside an 8k one must not
        # disable the amortizer for the long row; shallow rows take the
        # single-chunk step next loop iteration. Paged mode needs no
        # per-chunk allocation: admission already covered the whole prompt.
        if self.prefill_depth > 1:
            deep = [
                (slot, st, rem)
                for slot, st in rows
                for rem in [
                    (len(st.request.prompt_ids) - st.done - 1) // c
                ]
                if rem >= 2
            ]
            if deep:
                d = min(min(rem for _, _, rem in deep), self.prefill_depth)
            if deep and d >= 2:
                D = self.prefill_depth
                tokens3 = np.zeros((D, P, c), dtype=np.int32)
                slots_m = np.zeros((P,), dtype=np.int32)
                starts_m = np.zeros((P,), dtype=np.int32)
                for i, (slot, st, _) in enumerate(deep):
                    ids = st.request.prompt_ids
                    for j in range(d):
                        lo = st.done + j * c
                        tokens3[j, i, :] = ids[lo : lo + c]
                    slots_m[i] = slot
                    starts_m[i] = st.done
                for i in range(len(deep), P):  # pad rows duplicate row 0
                    tokens3[:, i, :] = tokens3[:, 0, :]
                    slots_m[i], starts_m[i] = slots_m[0], starts_m[0]
                t0 = time.time()
                self._push_table()
                margs = (
                    self.params, self.cache, self._up(tokens3),
                    self._up(slots_m), self._up(starts_m),
                    self._up(np.int32(d)),
                )
                if self.spec_tokens:
                    self.cache, self._history_dev = (
                        self._prefill_multi_chunk_hist(
                            *margs, self._history_dev, self._aids_dev
                        )
                    )
                else:
                    self.cache = self._prefill_multi_chunk(
                        *margs, self._aids_dev
                    )
                if self._lockstep:
                    self._jax.block_until_ready(self.cache.lengths)
                for _, st, _ in deep:
                    st.done += d * c
                if self._metrics is not None:
                    self._metrics.record_histogram(
                        "app_tpu_infer_latency", time.time() - t0,
                        "kind", "prefill_multi",
                    )
                return True

        tokens = np.zeros((P, c), dtype=np.int32)
        slots = np.zeros((P,), dtype=np.int32)
        starts = np.zeros((P,), dtype=np.int32)
        lens = np.zeros((P,), dtype=np.int32)
        finalize = np.zeros((P,), dtype=bool)
        row_valid = np.zeros((P,), dtype=bool)
        temps = np.ones((P,), dtype=np.float32)
        topps = np.ones((P,), dtype=np.float32)
        greedy = np.ones((P,), dtype=bool)
        for i, (slot, st) in enumerate(rows):
            ids = st.request.prompt_ids
            chunk = ids[st.done : st.done + c]
            tokens[i, : len(chunk)] = chunk
            slots[i] = slot
            starts[i] = st.done
            lens[i] = len(chunk)
            finalize[i] = st.done + len(chunk) >= len(ids)
            row_valid[i] = True
            temps[i] = max(st.request.temperature, 0.0)
            topps[i] = st.request.top_p
            greedy[i] = st.request.temperature <= 0
        for i in range(len(rows), P):
            # Padding rows duplicate row 0: identical K/V writes to the
            # same cache positions are idempotent, and row_valid=False
            # keeps them out of the finalize merge.
            tokens[i] = tokens[0]
            slots[i], starts[i], lens[i] = slots[0], starts[0], lens[0]
            temps[i], greedy[i], topps[i] = temps[0], greedy[0], topps[0]

        jnp = self._jnp
        t0 = time.time()
        self._push_table()
        args = (
            self.params, self.cache, self._up(tokens),
            self._up(slots), self._up(starts), self._up(lens),
            self._up(finalize), self._up(row_valid),
            self._up(temps), self._up(greedy), self._up(topps),
            self._seeds_dev, self._tokens_dev, self._logps_dev,
            self._pcounts_dev, self._nsteps_dev, self._bidx_dev,
            self._bval_dev, self._topi_dev, self._topl_dev,
            self._aids_dev,
        )
        # Static compile choice: the no-bias program has no bias scatter
        # at all (each variant compiles once, then caches).
        use_bias = any(
            st.request.logit_bias for _, st in rows
        )
        if self.spec_tokens:
            (self.cache, self._tokens_dev, self._logps_dev, first_dev,
             first_lp_dev, self._pcounts_dev, self._nsteps_dev,
             self._topi_dev, self._topl_dev, ftopi_dev, ftopl_dev,
             self._history_dev) = (
                self._prefill_chunk_step_hist(
                    *args, self._history_dev, use_bias=use_bias
                )
            )
        else:
            (self.cache, self._tokens_dev, self._logps_dev, first_dev,
             first_lp_dev, self._pcounts_dev, self._nsteps_dev,
             self._topi_dev, self._topl_dev, ftopi_dev, ftopl_dev) = (
                self._prefill_chunk_step(*args, use_bias=use_bias)
            )
        if self._lockstep:
            self._jax.block_until_ready(first_dev)
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "prefill"
            )
            self._metrics.record_histogram(
                "app_tpu_batch_size", len(rows), "batcher", "prefill"
            )

        emits_started = False
        for i, (slot, st) in enumerate(rows):
            st.done += int(lens[i])
            if finalize[i]:
                st.request.effective_prompt_len = st.done
                del self._prefilling[slot]
                if st.request.prefix_store:
                    # Park the rows in the pool instead of decoding; the
                    # slot goes straight back to the free list. A prefix
                    # whose adapter was reloaded/unloaded while this
                    # prefill was in flight prefilled under the WRONG
                    # weights — drop it (resolve -1) instead of
                    # registering stale K/V under a reusable slot id.
                    r_aid = st.request.aid
                    if r_aid and st.request.lora_gen != self._lora_gen[r_aid]:
                        if not st.request.future.done():
                            st.request.future.set_result(-1)
                    else:
                        idx = self._prefix_pool.store(
                            st.request.prompt_ids, self.cache, slot,
                            r_aid,
                        )
                        if not st.request.future.done():
                            st.request.future.set_result(idx)
                    st.request.stream.put(None)
                else:
                    seq = _ActiveSeq(request=st.request, last_token=-1)
                    self._slots[slot] = seq
                    self._slot_state_dirty = True
                    # Early first-token emission: the chunk step SAMPLED this
                    # row's first token on device — fetch it asynchronously
                    # and emit the moment it lands (~prefill + one-way RTT)
                    # instead of after the first decode window drains through
                    # the pipeline (~3 windows ≈ 300 ms on the relay).
                    if not emits_started:
                        emits_started = True
                        fetches = [first_dev, first_lp_dev]
                        if self.top_logprobs:
                            fetches += [ftopi_dev, ftopl_dev]
                        for arr in fetches:
                            try:
                                arr.copy_to_host_async()
                            except AttributeError:
                                pass
                    self._prefill_emits.append(
                        (first_dev, first_lp_dev, ftopi_dev, ftopl_dev, i,
                         slot, seq)
                    )
        self._update_slot_gauges()
        return True

    def _flush_prefill_emits(self) -> None:
        """Emit first tokens whose async prefill fetch has landed.

        Non-blocking (``is_ready`` poll); each entry emits at most once —
        if a decode window's processing got there first (the loaded case),
        the entry is dropped.
        """
        if not self._prefill_emits:
            return
        keep = []
        for entry in self._prefill_emits:
            first_dev, lp_dev, ftopi_dev, ftopl_dev, row, slot, seq = entry
            req = seq.request
            # The window emission path won the race (token already out),
            # or the request is gone — nothing to do.
            if req.future.done() or req.token_ids or seq.first_emitted:
                continue
            try:
                if not first_dev.is_ready():
                    keep.append(entry)
                    continue
            except AttributeError:  # fake/CPU backends: always ready
                pass
            tok = int(np.asarray(first_dev)[row])
            lp = float(np.asarray(lp_dev)[row])
            top = None
            if self.top_logprobs and req.top_logprobs:
                ti = np.asarray(ftopi_dev)[row]
                tl = np.asarray(ftopl_dev)[row]
                top = [
                    (int(ti[j]), float(tl[j]))
                    for j in range(req.top_logprobs)
                ]
            now = time.time()
            req.ttft_s = now - req.enqueued_at
            seq.first_token_at = now
            seq.first_emitted = True
            seq.last_token = tok
            seq.n_generated += 1
            self._emit_token(seq, tok, lp, top)
            if self._finished(seq):
                self._retire(slot, seq)
                if self._slots[slot] is seq:
                    self._release_slot(slot)
        self._prefill_emits = keep

    def _dispatch_window(self):
        """Dispatch one k-step device window (non-blocking) and start the
        async device→host copy of its emitted block — [2, k, S] for plain
        decode, [2, k, S, G+1] plus a [k, S] counts array for speculative
        windows, [2, m*k, S] plus a windows-run scalar for mega windows.
        Returns ``(emitted_dev, counts_dev_or_None, slots_snapshot,
        t_dispatch, wrun_dev_or_None)`` for _process_window — the snapshot
        matters because by processing time a retired slot may already hold
        a NEW request admitted in between."""
        jnp = self._jnp
        if self._slot_state_dirty:
            # Slot composition changed since the last window: re-upload the
            # [n_slots] state vectors once. Steady-state windows skip this —
            # dispatch is then pure device work, no H2D copies at all.
            active = np.zeros((self.n_slots,), dtype=bool)
            temps = np.ones((self.n_slots,), dtype=np.float32)
            topps = np.ones((self.n_slots,), dtype=np.float32)
            greedy = np.ones((self.n_slots,), dtype=bool)
            fpen = np.zeros((self.n_slots,), dtype=np.float32)
            ppen = np.zeros((self.n_slots,), dtype=np.float32)
            for i, seq in enumerate(self._slots):
                if seq is not None:
                    active[i] = True
                    temps[i] = max(seq.request.temperature, 0.0)
                    topps[i] = seq.request.top_p
                    greedy[i] = seq.request.temperature <= 0
                    fpen[i] = seq.request.frequency_penalty
                    ppen[i] = seq.request.presence_penalty
            self._active_dev = self._up(active)
            self._temps_dev = self._up(temps)
            self._topp_dev = self._up(topps)
            self._greedy_dev = self._up(greedy)
            if self.enable_penalties:
                self._fpen_dev = self._up(fpen)
                self._ppen_dev = self._up(ppen)
            self._slot_state_dirty = False

        # Mega-window mode: compute each slot's remaining budget on the
        # host (it knows tokens_in_flight) and hand it to the device loop;
        # coverage accounting uses the same number so `wants_more` gating
        # stays exact (the device delivers ≥ min(m·k, remaining) steps per
        # slot — early exit only fires once every remaining hits 0 or EOS,
        # and an EOS slot is retired by processing, so accounting can
        # never strand a live slot).
        mega = self.mega_windows
        use_bias = any(
            seq is not None and seq.request.logit_bias
            for seq in self._slots
        )
        remaining_host = eos_stop_host = None
        cover = self.window_k * mega  # guaranteed MINIMUM emissions
        if mega > 1:
            remaining_host = np.zeros((self.n_slots,), dtype=np.int32)
            eos_stop_host = np.zeros((self.n_slots,), dtype=bool)
            for i, seq in enumerate(self._slots):
                if seq is not None:
                    remaining_host[i] = max(
                        0,
                        seq.request.max_new_tokens + 1 - seq.tokens_in_flight,
                    )
                    eos_stop_host[i] = seq.request.stop_on_eos

        if self.kv_block:
            # Allocation must stay AHEAD of the window about to be
            # dispatched (its writes land before the host sees the
            # tokens). A dry pool mid-stream fails the request — the
            # honest outcome of an oversubscribed pool.
            wt = self._window_tokens()
            for i, seq in enumerate(self._slots):
                if seq is None:
                    continue
                if mega > 1:
                    # Windows this slot still WRITES real K/V for: its
                    # remaining budget covers in ≤ ceil(remaining/k)
                    # windows (spec emits ≥ k/window); each window writes
                    # k*(G+1) positions. Junk past that parks at block 0.
                    k = self.window_k
                    windows_i = min(mega, -(-int(remaining_host[i]) // k))
                    wt = windows_i * k * (self.spec_tokens + 1)
                req = seq.request
                base = req.effective_prompt_len or len(req.prompt_ids)
                need = base + self._dispatched_tokens[i] + wt + 1
                if self._ensure_blocks(i, need):
                    self._dispatched_tokens[i] += wt
                    continue
                if not req.future.done():
                    req.future.set_exception(RuntimeError(
                        "KV block pool exhausted mid-generation "
                        "(raise TPU_KV_POOL_BLOCKS or lower concurrency)"
                    ))
                req.stream.put(None)
                self._release_slot(i)
                if mega > 1:
                    # remaining_host was computed before this loop; the
                    # device must not spin mega windows covering a slot
                    # whose request just failed.
                    remaining_host[i] = 0
                    eos_stop_host[i] = False
            self._push_table()

        for i, seq in enumerate(self._slots):
            if seq is not None:
                seq.tokens_in_flight += (
                    min(cover, int(remaining_host[i])) if mega > 1
                    else self.window_k
                )
        t0 = time.time()
        counts = None
        wrun = None
        etops = None
        if mega > 1 and self.spec_tokens:
            (emitted, counts, wrun, self._tokens_dev, self._logps_dev,
             self.cache, self._nsteps_dev, self._history_dev) = (
                self._mega_spec_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._history_dev, self._seeds_dev,
                    self._up(remaining_host), self._up(eos_stop_host),
                    self._aids_dev,
                    k=self.window_k, m=mega,
                )
            )
        elif mega > 1:
            (emitted, etops, wrun, self._tokens_dev, self._logps_dev,
             self.cache, self._nsteps_dev, self._pcounts_dev,
             self._topi_dev, self._topl_dev) = (
                self._mega_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._fpen_dev, self._ppen_dev, self._pcounts_dev,
                    self._seeds_dev, self._bidx_dev, self._bval_dev,
                    self._topi_dev, self._topl_dev,
                    self._up(remaining_host), self._up(eos_stop_host),
                    self._aids_dev,
                    k=self.window_k, m=mega, use_bias=use_bias,
                )
            )
        elif self.spec_tokens:
            (emitted, counts, self._tokens_dev, self._logps_dev, self.cache,
             self._nsteps_dev, self._history_dev) = (
                self._spec_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._history_dev, self._seeds_dev, self._aids_dev,
                    k=self.window_k,
                )
            )
        else:
            (emitted, etops, self._tokens_dev, self._logps_dev, self.cache,
             self._nsteps_dev, self._pcounts_dev, self._topi_dev,
             self._topl_dev) = (
                self._decode_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._fpen_dev, self._ppen_dev, self._pcounts_dev,
                    self._seeds_dev, self._bidx_dev, self._bval_dev,
                    self._topi_dev, self._topl_dev, self._aids_dev,
                    k=self.window_k, use_bias=use_bias,
                )
            )
        if etops is not None and not any(
            seq is not None and seq.request.top_logprobs
            for seq in self._slots
        ):
            # Nobody asked for alternatives: skip the [2, m*k, S, K]
            # device→host block entirely (the program computes it either
            # way; the fetch is what costs on the dispatch path).
            etops = None
        extras = [a for a in (counts, wrun, etops) if a is not None]
        for arr in (emitted, *extras):
            try:
                arr.copy_to_host_async()
            except AttributeError:  # older jax / fake backends
                pass
        if self._lockstep:
            self._jax.block_until_ready(emitted)
        return emitted, counts, list(self._slots), t0, wrun, etops

    def _process_window(self, emitted, counts, snapshot, t0, wrun=None,
                        etops=None) -> None:
        t_fetch = time.time()
        # Interruptible wait: while this window's block is in flight, flush
        # any prefill first-token fetches that land first (unloaded TTFT
        # would otherwise be gated on the window fetch). Mega mode also
        # keeps ADMITTING during the wait — prefill chunks for queued
        # requests ride the device queue behind the in-flight mega window,
        # overlapping next-wave admission with current-wave decode.
        if (self._prefill_emits or wrun is not None) and hasattr(
            emitted, "is_ready"
        ):
            while not emitted.is_ready():
                if wrun is not None:
                    self._dispatch_prefill_chunk()
                self._flush_prefill_emits()
                time.sleep(0.001)
        # Decode: [2, k, S] (mega: [2, m*k, S], first wrun*k valid).
        # Spec: [2, k, S, G+1] + counts [k, S].
        emitted_host = np.asarray(emitted)
        counts_host = np.asarray(counts) if counts is not None else None
        etops_host = np.asarray(etops) if etops is not None else None
        steps = (
            self.window_k if wrun is None
            else int(np.asarray(wrun)) * self.window_k
        )
        if self._metrics is not None:
            # decode_fetch = host-blocking time (what pipelining hides);
            # decode_window_pipeline = dispatch→processed incl. D windows
            # of pipeline queueing (NOT per-window device latency).
            now_m = time.time()
            self._metrics.record_histogram(
                "app_tpu_infer_latency", now_m - t_fetch, "kind", "decode_fetch"
            )
            self._metrics.record_histogram(
                "app_tpu_infer_latency", now_m - t0,
                "kind", "decode_window_pipeline",
            )

        now = time.time()
        for i, seq in enumerate(snapshot):
            if seq is None:
                continue
            if seq.request.future.done():
                # Retired by an earlier window's processing (overshoot
                # tokens — drop), or cancelled by the caller mid-flight:
                # free the slot or it would stay active forever.
                if self._slots[i] is seq:
                    seq.request.stream.put(None)
                    self._release_slot(i)
                continue
            if seq.request.ttft_s == 0.0:
                seq.request.ttft_s = now - seq.request.enqueued_at
                seq.first_token_at = now
            if counts_host is None:
                step_toks = (
                    ((emitted_host[0, step, i], emitted_host[1, step, i]),)
                    for step in range(steps)
                )  # enumerate() below recovers the step index for etops
            else:
                step_toks = (
                    tuple(
                        (emitted_host[0, step, i, j], emitted_host[1, step, i, j])
                        for j in range(int(counts_host[step, i]))
                    )
                    for step in range(steps)
                )
            want_top = (
                etops_host is not None and seq.request.top_logprobs
            )
            done = False
            for step, toks in enumerate(step_toks):
                for tok_f, lp in toks:
                    if seq.first_emitted and not seq.first_skip_done:
                        # This position repeats the prefill-sampled token
                        # that _flush_prefill_emits already emitted.
                        seq.first_skip_done = True
                        continue
                    tok = int(tok_f)
                    top = None
                    if want_top:
                        top = [
                            (int(etops_host[0, step, i, j]),
                             float(etops_host[1, step, i, j]))
                            for j in range(seq.request.top_logprobs)
                        ]
                    seq.last_token = tok
                    seq.n_generated += 1
                    self._emit_token(seq, tok, float(lp), top)
                    if self._finished(seq):
                        self._retire(i, seq)
                        if self._slots[i] is seq:
                            self._release_slot(i)
                        done = True
                        break
                if done:
                    break
        if counts_host is not None and self._metrics is not None:
            # Acceptance observability: tokens-per-live-step across the
            # window (1.0 = no draft accepted, spec_tokens+1 = all).
            live = counts_host > 0
            if live.any():
                self._metrics.record_histogram(
                    "app_tpu_spec_tokens_per_step",
                    float(counts_host[live].mean()),
                    "model", self.model_name,
                )
        self._update_slot_gauges()

    def _emit_token(self, seq: _ActiveSeq, tok: int, logprob: float,
                    top=None) -> None:
        if seq.request.top_logprobs:
            seq.request.token_top_logprobs.append(top)
        seq.request.token_ids.append(tok)
        seq.request.token_logprobs.append(logprob)
        seq.request.stream.put(tok)
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_tokens_generated", "model", self.model_name
            )

    def _finished(self, seq: _ActiveSeq) -> bool:
        req = seq.request
        eos = self.tokenizer.eos_id if self.tokenizer is not None else -1
        if req.stop_on_eos and req.token_ids and req.token_ids[-1] == eos:
            return True
        if req.stop_texts and self.tokenizer is not None:
            text = self.tokenizer.decode(req.token_ids)
            at = min(
                (p for p in (text.find(s) for s in req.stop_texts) if p != -1),
                default=-1,
            )
            if at != -1:
                req.stop_cut = at
                return True
        if len(req.token_ids) >= req.max_new_tokens:
            return True
        prompt_len = req.effective_prompt_len or len(req.prompt_ids)
        return prompt_len + len(req.token_ids) >= self.max_len - 1

    def _retire(self, slot: int, seq: _ActiveSeq) -> None:
        req = seq.request
        text = self.tokenizer.decode(req.token_ids) if self.tokenizer else ""
        ids, lps = list(req.token_ids), list(req.token_logprobs)
        tops = list(req.token_top_logprobs) if req.top_logprobs else None
        eos = self.tokenizer.eos_id if self.tokenizer is not None else -1
        if req.stop_cut >= 0:
            # Stop sequence: trim the text at the match and the token/
            # logprob lists to the longest prefix whose decode fits the
            # kept text, so text and logprobs stay aligned.
            text = text[: req.stop_cut]
            keep = 0
            for i in range(1, len(ids) + 1):
                if len(self.tokenizer.decode(ids[:i])) <= req.stop_cut:
                    keep = i
                else:
                    break
            ids, lps = ids[:keep], lps[:keep]
            if tops is not None:
                tops = tops[:keep]
            reason = "stop"
        elif req.stop_on_eos and ids and ids[-1] == eos:
            reason = "stop"
        else:
            reason = "length"  # token budget or context window exhausted
        result = GenerationResult(
            text=text,
            token_ids=ids,
            prompt_tokens=len(req.prompt_ids),
            ttft_s=req.ttft_s,
            duration_s=time.time() - req.enqueued_at,
            truncated=req.truncated,
            token_logprobs=lps,
            finish_reason=reason,
            token_top_logprobs=tops,
        )
        if not req.future.done():
            req.future.set_result(result)
        req.stream.put(None)  # stream sentinel (after the result resolves)

    def _update_slot_gauges(self) -> None:
        if self._metrics is None:
            return
        in_use = sum(1 for s in self._slots if s is not None)
        self._metrics.set_gauge("app_tpu_kv_slots_in_use", in_use, "model", self.model_name)
        self._metrics.set_gauge(
            "app_tpu_queue_depth", self._pending.qsize(), "batcher", "generate"
        )
        try:
            stats = self._jax.local_devices()[0].memory_stats() or {}
            if "bytes_in_use" in stats:
                self._metrics.set_gauge(
                    "app_tpu_hbm_used_bytes", stats["bytes_in_use"], "chip", "0"
                )
        except Exception:
            pass

    # ------------------------------------------------------------------
    # profiling (bench harness; VERDICT r1 weak #4 — know where time goes)
    # ------------------------------------------------------------------

    def profile_decode(self, n_windows: int = 8, prompt_len: int = 16) -> dict:
        """Measure device-only decode window time and the host↔device fetch
        RTT, with the engine stopped. Chains ``n_windows`` windows
        back-to-back with one final block, so the relay RTT amortizes out:
        ``window_s ≈ (total - rtt) / n_windows``.

        Returns ``{"window_s", "step_s", "rtt_s", "prefill_s"}``.
        """
        if self.family != "llm":
            raise RuntimeError("profile_decode is for llm engines")
        if self._running:
            raise RuntimeError("stop the engine before profiling")
        jax, jnp = self._jax, self._jnp
        B, P = self.n_slots, self.prefill_batch
        prompt_len = min(prompt_len, self.prefill_chunk)

        # Prefill ALL slots via chunk steps so decode reads realistic KV
        # prefixes. Timed on the last call (first pays compile).
        prefill_s = 0.0
        for base in range(0, B, P):
            rows = list(range(base, min(base + P, B)))
            tokens = np.ones((P, self.prefill_chunk), dtype=np.int32)
            slots = np.full((P,), rows[0], dtype=np.int32)
            slots[: len(rows)] = rows
            starts = np.zeros((P,), dtype=np.int32)
            lens = np.full((P,), prompt_len, dtype=np.int32)
            finalize = np.ones((P,), dtype=bool)
            row_valid = np.zeros((P,), dtype=bool)
            row_valid[: len(rows)] = True
            temps = np.ones((P,), dtype=np.float32)
            topps = np.ones((P,), dtype=np.float32)
            greedy = np.ones((P,), dtype=bool)
            t0 = time.perf_counter()
            (self.cache, self._tokens_dev, self._logps_dev, first, _flp,
             self._pcounts_dev, self._nsteps_dev, self._topi_dev,
             self._topl_dev, _fti, _ftl) = (
                self._prefill_chunk_step(
                    self.params, self.cache, self._up(tokens),
                    self._up(slots), self._up(starts), self._up(lens),
                    self._up(finalize), self._up(row_valid),
                    self._up(temps), self._up(greedy),
                    self._up(topps),
                    self._seeds_dev, self._tokens_dev, self._logps_dev,
                    self._pcounts_dev, self._nsteps_dev, self._bidx_dev,
                    self._bval_dev, self._topi_dev, self._topl_dev,
                    self._aids_dev,
                    use_bias=False,
                )
            )
            jax.block_until_ready(first)
            prefill_s = time.perf_counter() - t0

        # Fresh [B]-shaped vectors — the prefill loop's temps/greedy above
        # are [P]-shaped and P != B crashes the decode window.
        active = jnp.ones((B,), dtype=bool)
        tdev = jnp.ones((B,), dtype=jnp.float32)
        pdev = jnp.ones((B,), dtype=jnp.float32)
        gdev = jnp.ones((B,), dtype=bool)

        def window():
            out = self._decode_window(
                self.params, self._tokens_dev, self._logps_dev, self.cache,
                active, self._nsteps_dev, tdev, gdev, pdev,
                self._fpen_dev, self._ppen_dev, self._pcounts_dev,
                self._seeds_dev, self._bidx_dev, self._bval_dev,
                self._topi_dev, self._topl_dev, self._aids_dev,
                k=self.window_k, use_bias=False,
            )
            (emitted, _etops, self._tokens_dev, self._logps_dev, self.cache,
             self._nsteps_dev, self._pcounts_dev, self._topi_dev,
             self._topl_dev) = out
            return emitted

        # Warmup (compile) + RTT probe: a blocking fetch of a just-computed
        # tiny array is ~one relay roundtrip.
        jax.block_until_ready(window())
        rtts = []
        for _ in range(5):
            x = self._tokens_dev + 1
            t0 = time.perf_counter()
            np.asarray(x)
            rtts.append(time.perf_counter() - t0)
        rtt_s = sorted(rtts)[len(rtts) // 2]

        t0 = time.perf_counter()
        last = None
        for _ in range(n_windows):
            last = window()
        jax.block_until_ready(last)
        total = time.perf_counter() - t0
        window_s = max(total - rtt_s, 1e-9) / n_windows

        # Reset cache lengths so profiling state can't leak into serving.
        self.cache = self.cache._replace(
            lengths=jnp.zeros_like(self.cache.lengths)
        )
        self._slot_state_dirty = True
        return {
            "window_s": window_s,
            "step_s": window_s / self.window_k,
            "rtt_s": rtt_s,
            "prefill_s": prefill_s,
        }

    def param_bytes(self) -> int:
        from gofr_tpu.ops.quant import quantized_bytes

        return quantized_bytes(self.params)

    # ------------------------------------------------------------------
    # public LLM API
    # ------------------------------------------------------------------

    @property
    def max_prompt_tokens(self) -> int:
        """Longest admissible prompt: one generated token plus pipelined-
        window overshoot must still fit in max_len (the same invariant the
        admission-room clamp in _dispatch_prefill_chunk enforces)."""
        return self.max_len - 2 - (self.pipeline_depth + 1) * self.window_k

    def _enqueue(self, req: _GenRequest) -> None:
        # Check-and-enqueue under the drain lock: once the scheduler's final
        # drain has run, nothing may land in the queue (it would hang) —
        # and during a GRACEFUL drain nothing may land either (503; the
        # same lock the scheduler's idle confirmation takes, so a request
        # can never slip in after the drain observed the engine idle).
        with self._submit_lock:
            if self._draining:
                from gofr_tpu.errors import ErrorServiceUnavailable

                raise ErrorServiceUnavailable(
                    "engine draining for shutdown; retry against another "
                    "replica"
                )
            if self._fatal is not None:
                raise RuntimeError(f"engine scheduler died: {self._fatal}")
            if not self._running or self._drained:
                raise RuntimeError("engine not started")
            self._pending.put_nowait(req)
            self._sched_idle = False
        self._work.set()

    def submit_generate(
        self,
        prompt: str | list[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        stop_on_eos: bool = True,
        stop: "Optional[list[str]]" = None,
        top_p: float = 1.0,
        frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0,
        seed: "Optional[int]" = None,
        logit_bias: "Optional[dict]" = None,
        top_logprobs: int = 0,
        adapter: str = "",
    ) -> _GenRequest:
        if self.family != "llm":
            raise RuntimeError(f"model {self.model_name} is not a generative LLM")
        aid = 0
        if adapter:
            from gofr_tpu.errors import ErrorInvalidParam

            if adapter not in self._lora_names:
                raise ErrorInvalidParam([
                    f"unknown LoRA adapter {adapter!r}; loaded: "
                    f"{sorted(self._lora_names)}"
                ])
            aid = self._lora_names[adapter]
        if not 0.0 < top_p <= 1.0:
            from gofr_tpu.errors import ErrorInvalidParam

            raise ErrorInvalidParam(["top_p must be in (0, 1]"])
        if top_p < 1.0 and not self.enable_top_p:
            from gofr_tpu.errors import ErrorInvalidParam

            raise ErrorInvalidParam([
                "top_p requires TPU_TOP_P=true (compiles the nucleus "
                "sort into the sampler)"
            ])
        if frequency_penalty or presence_penalty:
            from gofr_tpu.errors import ErrorInvalidParam

            if not self.enable_penalties:
                raise ErrorInvalidParam([
                    "frequency/presence penalties require TPU_PENALTIES="
                    "true (compiles the per-slot token-count plane into "
                    "the sampler)"
                ])
            if not (-2.0 <= frequency_penalty <= 2.0
                    and -2.0 <= presence_penalty <= 2.0):
                raise ErrorInvalidParam([
                    "penalties must be in [-2, 2]"
                ])
        if top_logprobs:
            from gofr_tpu.errors import ErrorInvalidParam

            if not 0 < int(top_logprobs) <= self.top_logprobs:
                raise ErrorInvalidParam([
                    f"top_logprobs must be in [1, {self.top_logprobs}] "
                    f"(the engine compiles TPU_TOP_LOGPROBS="
                    f"{self.top_logprobs} alternatives)"
                    if self.top_logprobs else
                    "top_logprobs requires TPU_TOP_LOGPROBS>0 (compiles "
                    "the per-step alternatives top_k into the sampler)"
                ])
        bias: dict = {}
        if logit_bias:
            from gofr_tpu.errors import ErrorInvalidParam

            if not isinstance(logit_bias, dict):
                raise ErrorInvalidParam([
                    "logit_bias must be an object mapping token ids to "
                    "numbers"
                ])
            if self.spec_tokens:
                raise ErrorInvalidParam([
                    "logit_bias is not supported with speculative "
                    "decoding (TPU_SPEC_TOKENS) — biased greedy picks "
                    "would invalidate the draft-acceptance rule"
                ])
            if len(logit_bias) > LOGIT_BIAS_K:
                raise ErrorInvalidParam([
                    f"logit_bias supports at most {LOGIT_BIAS_K} entries"
                ])
            try:
                if any(
                    isinstance(t, float) and t != int(t) for t in logit_bias
                ):
                    raise ValueError("fractional token id")
                bias = {
                    int(t): float(b) for t, b in logit_bias.items()
                }
            except (TypeError, ValueError):
                raise ErrorInvalidParam([
                    "logit_bias must map integral token ids to numbers"
                ]) from None
            if any(
                not 0 <= t < self.cfg.vocab_size for t in bias
            ) or any(not -100.0 <= b <= 100.0 for b in bias.values()):
                raise ErrorInvalidParam([
                    f"logit_bias token ids must be in [0, "
                    f"{self.cfg.vocab_size}) and biases in [-100, 100]"
                ])
        ids = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        )
        # Overlong prompts are REJECTED up front (ErrorPromptTooLong → 413)
        # unless truncation was explicitly enabled, in which case the tail
        # is kept and the result is flagged (VERDICT r1 weak #8: never
        # silently drop prompt content).
        max_prompt = self.max_prompt_tokens
        truncated = False
        if len(ids) > max_prompt:
            if not self.truncate_prompts:
                from gofr_tpu.errors import ErrorPromptTooLong

                raise ErrorPromptTooLong(len(ids), max_prompt)
            ids = ids[-max_prompt:]
            truncated = True
            if self._logger is not None:
                self._logger.warnf(
                    "prompt truncated to its last %d tokens "
                    "(TPU_TRUNCATE_PROMPTS)", max_prompt,
                )
        req = _GenRequest(
            prompt_ids=ids,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            stop_on_eos=stop_on_eos,
            truncated=truncated,
            stop_texts=list(stop or []),
            top_p=top_p,
            frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty,
            # Unseeded requests draw a fresh seed (distinct streams);
            # int32 range for the device plane.
            seed=(
                int(seed) & 0x7FFFFFFF if seed is not None
                else self._seed_rng.getrandbits(31)
            ),
            logit_bias=bias,
            top_logprobs=int(top_logprobs or 0),
            aid=aid,
        )
        self._enqueue(req)
        return req

    def load_lora(self, name: str, source) -> int:
        """Load a LoRA adapter into a free adapter slot under ``name``.

        source: an HF PEFT checkpoint dir (``adapter_config.json`` +
        safetensors) or a raw ``{target: (a [L, d_in, r], b [L, r,
        d_out])}`` dict. Re-loading an existing name overwrites its slot.
        Returns the adapter slot index (≥1). Safe while serving: leaf
        updates build new device arrays; in-flight windows keep the old
        tree, and the name routes to the slot only after the write lands.
        """
        if self.family != "llm":
            raise RuntimeError("LoRA adapters are for llm engines")
        if not self.lora_slots:
            raise RuntimeError(
                "engine compiled without adapter slots — set "
                "TPU_LORA_SLOTS>0"
            )
        from gofr_tpu.serving.lora import (
            load_peft_adapter,
            validate_adapter_leaves,
        )

        if isinstance(source, str):
            leaves = load_peft_adapter(
                source, self.cfg, self.lora_rank, self._lora_targets
            )
        else:
            leaves = dict(source)
            validate_adapter_leaves(
                leaves, self.cfg, self.lora_rank, self._lora_targets
            )
        idx = self._lora_names.get(name)
        if idx is None:
            used = set(self._lora_names.values())
            idx = next(
                (
                    i
                    for i in range(1, self.lora_slots + 1)
                    if i not in used
                ),
                None,
            )
            if idx is None:
                raise RuntimeError(
                    f"all {self.lora_slots} adapter slots in use "
                    f"(TPU_LORA_SLOTS); unload_lora one first"
                )
        # New weights for this slot: invalidate pooled prefixes computed
        # under the previous occupant (reload keeps the same idx; a fresh
        # idx may still have stale entries from a late in-flight store).
        self._lora_gen[idx] += 1
        if self._prefix_pool is not None:
            self._prefix_pool.purge_aid(idx)
        layers = dict(self.params["layers"])
        # Zero the WHOLE slot first: a reload with fewer targets than the
        # previous version must not leave the old version's deltas live.
        for t in self._lora_targets:
            if t in leaves:
                continue
            for suffix in ("_lora_a", "_lora_b"):
                leaf = layers[t + suffix]
                layers[t + suffix] = (
                    leaf.at[:, idx].set(self._jnp.zeros_like(leaf[:, idx]))
                )
        for t, (a, b) in leaves.items():
            dt = self.cfg.dtype
            layers[t + "_lora_a"] = (
                layers[t + "_lora_a"].at[:, idx].set(a.astype(dt))
            )
            layers[t + "_lora_b"] = (
                layers[t + "_lora_b"].at[:, idx].set(b.astype(dt))
            )
        self.params = {**self.params, "layers": layers}
        self._lora_names[name] = idx
        if self._logger is not None:
            self._logger.infof(
                "LoRA adapter %s loaded into slot %d (targets: %s)",
                name, idx, ",".join(sorted(leaves)),
            )
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_lora_adapters", float(len(self._lora_names)),
                "model", self.model_name,
            )
        return idx

    def unload_lora(self, name: str) -> None:
        """Zero ``name``'s adapter slot and free it. In-flight requests
        routed to the slot finish against the zeroed (= base) weights —
        callers should drain first if that matters."""
        idx = self._lora_names.pop(name, None)
        if idx is None:
            raise KeyError(f"no loaded LoRA adapter {name!r}")
        self._lora_gen[idx] += 1
        if self._prefix_pool is not None:
            # The adapter slot id may be reused by a later load; pooled
            # prefixes prefilled under it are stale the moment it frees.
            self._prefix_pool.purge_aid(idx)
        layers = dict(self.params["layers"])
        for t in self._lora_targets:
            for suffix in ("_lora_a", "_lora_b"):
                leaf = layers[t + suffix]
                layers[t + suffix] = (
                    leaf.at[:, idx].set(self._jnp.zeros_like(leaf[:, idx]))
                )
        self.params = {**self.params, "layers": layers}
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_lora_adapters", float(len(self._lora_names)),
                "model", self.model_name,
            )

    def lora_names(self) -> list[str]:
        """Loaded adapter names (OpenAI surface lists them as models)."""
        if self.family != "llm" or not getattr(self, "lora_slots", 0):
            return []
        return sorted(self._lora_names)

    def register_prefix(
        self, prompt: str | list[int], adapter: str = ""
    ) -> _GenRequest:
        """Prefill a shared prompt prefix ONCE and park its KV rows in the
        device prefix pool; later prompts starting with it skip straight
        to their remainder (admission-time row copy). The request's future
        resolves with the pool row index. Requires ``prefix_slots > 0``
        (``TPU_PREFIX_SLOTS``). With ``adapter``, the prefix prefills
        under that LoRA adapter and only same-adapter requests reuse it."""
        if self.family != "llm":
            raise RuntimeError("prefix registration is for llm engines")
        aid = 0
        if adapter:
            if adapter not in self._lora_names:
                raise KeyError(f"no loaded LoRA adapter {adapter!r}")
            aid = self._lora_names[adapter]
        if self._prefix_pool is None:
            raise RuntimeError(
                "prefix pool disabled — construct the engine with "
                "prefix_slots > 0 (TPU_PREFIX_SLOTS)"
            )
        ids = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str)
            else list(prompt)
        )
        if not ids:
            raise ValueError("prefix must be at least one token")
        if len(ids) > self.max_prompt_tokens:
            from gofr_tpu.errors import ErrorPromptTooLong

            raise ErrorPromptTooLong(len(ids), self.max_prompt_tokens)
        req = _GenRequest(
            prompt_ids=ids, max_new_tokens=1, temperature=0.0,
            stop_on_eos=False, prefix_store=True, aid=aid,
            lora_gen=self._lora_gen[aid] if aid else 0,
        )
        self._enqueue(req)
        return req

    def register_prefix_sync(
        self, prompt, timeout: float = 300.0, adapter: str = ""
    ) -> int:
        return self.register_prefix(prompt, adapter=adapter).future.result(
            timeout=timeout
        )

    def generate_sync(self, prompt, timeout: float = 300.0, **kw) -> GenerationResult:
        return self.submit_generate(prompt, **kw).future.result(timeout=timeout)

    async def generate(self, prompt, **kw) -> GenerationResult:
        req = self.submit_generate(prompt, **kw)
        return await asyncio.wrap_future(req.future)

    async def generate_stream(self, prompt, **kw):
        """Async iterator over generated token ids."""
        req = self.submit_generate(prompt, **kw)
        loop = asyncio.get_running_loop()
        while True:
            tok = await loop.run_in_executor(None, req.stream.get)
            if tok is None:
                return
            yield tok

    # ------------------------------------------------------------------
    # encoder / vision APIs (dynamic batching)
    # ------------------------------------------------------------------

    def _execute_embed(self, texts: list) -> list:
        jnp = self._jnp
        encoded = [
            self.tokenizer.encode(t)[: self.max_len] if isinstance(t, str) else list(t)
            for t in texts
        ]
        bucket = pad_bucket(max(len(e) for e in encoded), _PREFILL_BUCKETS)
        bucket = min(bucket, self.max_len)
        tokens = np.zeros((len(encoded), bucket), dtype=np.int32)
        mask = np.zeros((len(encoded), bucket), dtype=np.int32)
        for i, ids in enumerate(encoded):
            ids = ids[:bucket]
            tokens[i, : len(ids)] = ids
            mask[i, : len(ids)] = 1
        t0 = time.time()
        out = np.asarray(
            self._embed_step(self.params, jnp.asarray(tokens), jnp.asarray(mask))
        )
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "embed"
            )
        return [out[i] for i in range(len(encoded))]

    def _execute_classify(self, images: list) -> list:
        jnp = self._jnp
        batch = np.stack([np.asarray(img, dtype=np.float32) for img in images])
        t0 = time.time()
        logits = np.asarray(self._classify_step(self.params, jnp.asarray(batch)))
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "classify"
            )
        return [logits[i] for i in range(len(images))]

    def _execute_seq2seq(self, texts: list) -> list:
        jnp = self._jnp
        encoded = [
            self.tokenizer.encode(t)[: self.max_len]
            if isinstance(t, str) else list(t)
            for t in texts
        ]
        bucket = pad_bucket(max(len(e) for e in encoded), _PREFILL_BUCKETS)
        bucket = min(bucket, self.max_len)
        tokens = np.zeros((len(encoded), bucket), dtype=np.int32)
        lengths = np.zeros((len(encoded),), dtype=np.int32)
        for i, ids in enumerate(encoded):
            ids = ids[:bucket]
            tokens[i, : len(ids)] = ids
            lengths[i] = len(ids)
        t0 = time.time()
        out = np.asarray(self._seq2seq_step(
            self.params, jnp.asarray(tokens), jnp.asarray(lengths)
        ))
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "seq2seq"
            )
        eos = self.spec.eos_token
        results = []
        for i in range(len(encoded)):
            ids = out[i].tolist()
            # Trim at EOS only: pad zeros exist solely AFTER an emitted
            # EOS (t5_generate), and id 0 is a legitimate vocab token a
            # model may emit mid-sequence.
            if eos in ids:
                ids = ids[: ids.index(eos)]
            results.append(ids)
        return results

    def seq2seq_sync(self, text, timeout: float = 120.0) -> list:
        """Text-to-text generation (T5 family): returns generated token
        ids (EOS-trimmed, unpadded)."""
        return self._batcher.submit(text).result(timeout=timeout)

    async def seq2seq(self, text) -> list:
        return await asyncio.wrap_future(self._batcher.submit(text))

    async def seq2seq_text(self, text) -> tuple:
        """(decoded_text, token_ids) — the ONE dispatch-and-decode used
        by ctx.infer and both gRPC surfaces, so reply shaping can't
        drift between them."""
        ids = await self.seq2seq(text)
        decoded = (
            self.tokenizer.decode(ids) if self.tokenizer is not None else ""
        )
        return decoded, ids

    def embed_sync(self, text, timeout: float = 60.0) -> np.ndarray:
        return self._batcher.submit(text).result(timeout=timeout)

    async def embed(self, text) -> np.ndarray:
        return await asyncio.wrap_future(self._batcher.submit(text))

    def classify_sync(self, image, timeout: float = 60.0) -> np.ndarray:
        return self._batcher.submit(image).result(timeout=timeout)

    async def classify(self, image) -> np.ndarray:
        return await asyncio.wrap_future(self._batcher.submit(image))

    # ------------------------------------------------------------------
    # generic dispatch + health (container contract)
    # ------------------------------------------------------------------

    async def infer(self, inputs: Any, model: str = "", **kw) -> Any:
        """`ctx.infer` seam: dispatch on family."""
        if self.family == "llm":
            result = await self.generate(inputs, **kw)
            return {
                "text": result.text,
                "tokens": len(result.token_ids),
                "ttft_ms": round(result.ttft_s * 1e3, 2),
            }
        if self.family == "encoder":
            emb = await self.embed(inputs)
            return {"embedding": emb.tolist()}
        if self.family == "seq2seq":
            text, ids = await self.seq2seq_text(inputs)
            return {"text": text, "token_ids": ids}
        vec = await self.classify(inputs)
        return {"logits": vec.tolist(), "class": int(np.argmax(vec))}

    def infer_sync(self, inputs: Any, model: str = "", **kw) -> Any:
        if self.family == "llm":
            result = self.generate_sync(inputs, **kw)
            return {
                "text": result.text,
                "tokens": len(result.token_ids),
                "ttft_ms": round(result.ttft_s * 1e3, 2),
            }
        if self.family == "encoder":
            return {"embedding": self.embed_sync(inputs).tolist()}
        if self.family == "seq2seq":
            ids = self.seq2seq_sync(inputs)
            text = (
                self.tokenizer.decode(ids)
                if self.tokenizer is not None else ""
            )
            return {"text": text, "token_ids": ids}
        vec = self.classify_sync(inputs)
        return {"logits": vec.tolist(), "class": int(np.argmax(vec))}

    def health_check(self) -> dict:
        devices = self._jax.devices()
        details: dict[str, Any] = {
            "model": self.model_name,
            "family": self.family,
            "devices": [str(d) for d in devices],
            "running": self._running,
        }
        if self.family == "llm":
            details["kv_slots"] = {
                "total": self.n_slots,
                "in_use": sum(1 for s in self._slots if s is not None),
            }
            details["max_len"] = self.max_len
            details["pending"] = self._pending.qsize()
            details["prefilling"] = len(self._prefilling)
            if self.kv_block:
                details["kv_blocks"] = {
                    "block": self.kv_block,
                    "total": self.cache.n_blocks - 1,  # block 0 parks
                    "free": len(self._free_blocks),
                }
        try:
            stats = devices[0].memory_stats()
            if stats:
                details["hbm"] = {
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
        except Exception:  # noqa: BLE001 — not all backends report memory
            pass
        return {"status": "UP" if self._running else "DOWN", "details": details}
