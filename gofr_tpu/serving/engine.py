"""The TPU inference engine (net-new; SURVEY §2.6).

The container's ``tpu`` member (role of ``gofr.TPU()`` in the north star):
owns the model params on device, the jitted prefill/decode steps, the slot
KV cache, and the scheduler that turns concurrent requests into batched
device executions.

Design:

* **LLM family — continuous batching.** A dedicated scheduler thread admits
  pending prompts into free KV slots (prefill, bucketed padding) and steps
  ALL slots through one fused decode+sample kernel per token. Device-side
  sampling (per-slot temperature array + greedy mask inside the jit) means
  only ``[n_slots] int32`` crosses the host boundary per step. Cache buffers
  are donated so XLA updates them in place.
* **Encoder / vision families — dynamic batching.** Requests coalesce in a
  :class:`DynamicBatcher` (size/deadline flush) and execute as one padded
  batch.
* **Observability** rides the framework metrics registry: queue depth, KV
  slots in use, batch sizes, infer latency, tokens generated, HBM gauges.
"""

from __future__ import annotations

import asyncio
import math
import os
import queue
import threading

import time
from functools import partial
from typing import Any, AsyncIterator, Callable, Optional

import numpy as np

from gofr_tpu.analysis import lockcheck
from gofr_tpu import faults
from gofr_tpu.serving.batcher import DynamicBatcher
from gofr_tpu.serving.tokenizer import tokenizer_from_config

from gofr_tpu.serving.lifecycle import (
    AggregateThroughput,
    ClassPriorityQueue,
    CancelToken,
    Deadline,
    coalesce_deadline,
)
from gofr_tpu.serving.lora_runtime import LoRARuntimeMixin
from gofr_tpu.serving.modalities import ModalityMixin
from gofr_tpu.serving.programs import LLMProgramsMixin
from gofr_tpu.serving.scheduler import SchedulerMixin
from gofr_tpu.serving.types import (
    _ActiveSeq,
    _GenRequest,
    _PrefillState,
    GenerationResult,
    LOGIT_BIAS_K,
)
from gofr_tpu.serving.watchdog import Watchdog

# Draft length the TPU_SPEC_TOKENS=auto default resolves to where the
# bench gate holds (BENCH_SPEC_WORKLOAD: G=2 is the measured knee —
# longer drafts inflate the per-step decode-forward count faster than
# n-gram acceptance grows).
SPEC_AUTO_TOKENS = 2


def resolve_spec_tokens(
    raw: str,
    backend: str,
    enable_penalties: bool,
    top_logprobs: int,
) -> "tuple[int, Optional[str]]":
    """Resolve ``TPU_SPEC_TOKENS`` (``auto``/int) to a draft count.

    ``auto`` — the default — flips speculation ON exactly where the
    two-metric bench gate holds, and OFF where it measurably does not:

    * The numerics-exact spec window runs the decode-step program once
      per candidate position, so device compute per emitted token is
      never below the plain decode window's; speculation's entire win
      is per-dispatch amortization (an accepted draft means fewer
      windows — fewer host↔device round trips and scheduler passes —
      per token). On dispatch/host-overhead-bound TPU serving (the
      regime ``app_tpu_loop_host_overhead_ratio`` measures) the
      BENCH_SPEC_WORKLOAD A/B holds: tok/s up, host overhead flat. On
      compute-bound backends (CPU) the same A/B measures tok/s DOWN —
      the extra forwards dominate — so ``auto`` resolves to 0 there
      rather than shipping the gate's own counterexample.
    * Compile features the spec window's emission block excludes
      (penalties' evolving count plane, the top_logprobs alternatives
      plane) win over an *implicit* default: ``auto`` resolves to 0
      with a boot note instead of refusing to boot. An EXPLICIT
      ``TPU_SPEC_TOKENS>0`` alongside them still raises in the
      constructor — that combination is a contradiction the user
      typed, not one a default created.

    Returns ``(spec_tokens, note)``; ``note`` explains any auto
    resolution so boots are attributable in logs.
    """
    val = (raw or "auto").strip().lower()
    if val == "auto":
        conflicts = [
            name
            for name, on in (
                ("TPU_PENALTIES", enable_penalties),
                ("TPU_TOP_LOGPROBS", top_logprobs > 0),
            )
            if on
        ]
        if conflicts:
            return 0, (
                "speculative decoding default-on skipped: "
                + "/".join(conflicts)
                + " needs per-step planes the spec window's emission "
                "block excludes (set TPU_SPEC_TOKENS explicitly to "
                "choose the other way)"
            )
        if backend != "tpu":
            return 0, (
                f"speculative decoding stays off on backend={backend!r}: "
                "the exact verify pays one decode forward per emitted "
                "token, and the BENCH_SPEC_WORKLOAD gate (tok/s up AND "
                "host_overhead_ratio flat) only holds on dispatch-bound "
                "TPU serving (set TPU_SPEC_TOKENS>0 to force)"
            )
        return SPEC_AUTO_TOKENS, (
            f"speculative decoding ON by default (G={SPEC_AUTO_TOKENS}, "
            "numerics-exact verify; TPU_SPEC_TOKENS=0 disables)"
        )
    try:
        n = int(val)
    except ValueError:
        raise ValueError(
            f"TPU_SPEC_TOKENS={raw!r}: expected an integer or 'auto'"
        ) from None
    return max(0, n), None


class InferenceEngine(
    LLMProgramsMixin, SchedulerMixin, LoRARuntimeMixin, ModalityMixin
):
    """One loaded model + its serving machinery (facade over the
    program-builder, scheduler, adapter-runtime, and modality
    mixins)."""

    def __init__(
        self,
        model_name: str,
        *,
        n_slots: int = 8,
        max_len: int = 1024,
        max_batch: int = 8,
        max_wait_s: float = 0.005,
        window_k: int = 8,
        pipeline_depth: int = 2,
        mega_windows: int = 0,
        prefill_depth: int = 1,
        prefill_chunk: int = 256,
        prefill_batch: int = 8,
        truncate_prompts: bool = False,
        top_k: int = 0,
        enable_top_p: bool = False,
        enable_penalties: bool = False,
        top_logprobs: int = 0,
        spec_tokens: int = 0,
        kv_block: int = 0,
        kv_pool_blocks: int = 0,
        auto_prefix: bool = False,
        prefix_cache_blocks: int = 0,
        prefix_evict_watermark: int = 0,
        prefix_evict_hbm_frac: float = 0.0,
        admit_min_headroom: float = 0.0,
        hbm_budget_bytes: int = 0,
        mesh: Any = None,
        tp: int = 0,
        devices: Any = None,
        quant: str = "",
        kv_quant: str = "",
        prefix_slots: int = 0,
        lora_slots: int = 0,
        lora_rank: int = 16,
        lora_targets: str = "wq,wk,wv,wo",
        queue_max: int = 1024,
        queue_max_tokens: int = 0,
        class_promote_s: float = 5.0,
        tenant_queue_max: int = 0,
        tenant_ledger: Optional[bool] = None,
        tenant_label_max: int = 8,
        tenant_table_max: int = 256,
        tenant_fair_share: float = 0.0,
        slo_ttft_ms: float = 0.0,
        slo_e2e_ms: float = 0.0,
        slo_availability: float = 0.0,
        slo_tenant_objectives: Optional[dict] = None,
        brownout: Optional[bool] = None,
        brownout_enter: float = 2.0,
        brownout_exit: float = 1.0,
        brownout_sustain_s: float = 10.0,
        brownout_exit_sustain_s: float = 30.0,
        brownout_max_new: int = 256,
        brownout_aimd_cut: float = 0.5,
        brownout_recover_per_s: float = 0.02,
        brownout_min_headroom: float = 0.0,
        control_plane: Optional[bool] = None,
        control_stale_s: float = 10.0,
        control_tenant_enter: float = 2.0,
        control_tenant_exit: float = 1.0,
        control_tenant_sustain_s: float = 10.0,
        control_tenant_exit_sustain_s: float = 30.0,
        control_tenant_max_new: int = 256,
        control_tenant_aimd_cut: float = 0.5,
        control_tenant_recover_per_s: float = 0.02,
        control_tenant_table: int = 64,
        control_host_ratio: float = 0.85,
        control_host_util: float = 0.75,
        control_host_sustain_s: float = 30.0,
        control_predict_window_s: float = 60.0,
        control_predict_horizon_s: float = 30.0,
        control_predict_depth: float = 0.0,
        control_predict_hold_s: float = 30.0,
        queue_prefix_aware: bool = False,
        tenant_slo_class: str = "",
        compile_cache_dir: str = "",
        expected_tps: float = 0.0,
        watchdog_s: float = 0.0,
        replay_exact: bool = True,
        flight_recorder: Optional[bool] = None,
        flight_records: int = 256,
        flight_slow_s: float = 5.0,
        loop_profile: Optional[bool] = None,
        loop_stall_s: float = 1.0,
        loop_stall_factor: float = 10.0,
        loop_anomalies: int = 64,
        loop_trace_ms: int = 0,
        loop_trace_cooldown_s: float = 60.0,
        params: Any = None,
        logger: Any = None,
        metrics: Any = None,
        tokenizer: Any = None,
        seed: int = 0,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from gofr_tpu.models.registry import get_model

        self._jax, self._jnp = jax, jnp
        # Compile-cache persistence (TPU_COMPILE_CACHE_DIR): point jax's
        # persistent compilation cache at an operator-owned directory so
        # supervisor warm restarts and whole-process restarts re-LOAD
        # compiled executables instead of re-tracing. Wired FIRST —
        # before the params-init jit below, because jax initializes the
        # persistent cache lazily at the first compile and ignores a
        # later config write for the life of the process. Recorded on
        # the compile tracker (below) so health and /debug/capacity
        # show the cache's provenance.
        self._compile_cache_info: Optional[dict[str, Any]] = None
        if compile_cache_dir:
            cache_info: dict[str, Any] = {
                "dir": compile_cache_dir, "enabled": False,
            }
            try:
                jax.config.update(
                    "jax_compilation_cache_dir", compile_cache_dir
                )
                cache_info["enabled"] = True
            except Exception as exc:  # noqa: BLE001 — cache support varies by jax version; serving must boot without it
                cache_info["error"] = f"{type(exc).__name__}: {exc}"
            # Persist even trivial CPU-backend programs: the defaults
            # skip sub-second compiles, which is every program in the
            # deterministic test/bench environments where restart
            # behavior is pinned.
            for knob, val in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1),
            ):
                try:
                    jax.config.update(knob, val)
                except Exception:  # noqa: BLE001  # graftlint: disable=GL006 — optional tuning knob; older jax lacks it and the cache dir alone still works
                    pass
            # A sibling engine (or an import-time jit) may already have
            # initialized the lazy cache singleton dir-less — reset it
            # so THIS boot's dir takes effect.
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # noqa: BLE001  # graftlint: disable=GL006 — private seam; absent on some jax versions, where a fresh process honors the dir anyway
                pass
            self._compile_cache_info = cache_info
        self.model_name = model_name
        self.spec = get_model(model_name)
        self.family = self.spec.family
        self.cfg = self.spec.config
        self._logger = logger
        self._metrics = metrics
        self._top_k = top_k
        # Nucleus sampling support is a COMPILE choice: the per-step
        # [slots, vocab] sort only exists in the program when enabled.
        self.enable_top_p = bool(enable_top_p)
        # Frequency/presence penalties are a COMPILE choice too: the
        # [slots, vocab] generated-token count plane and its per-step
        # scatter only exist in the program when enabled.
        self.enable_penalties = bool(enable_penalties)
        if self.enable_penalties and spec_tokens > 0:
            raise ValueError(
                "TPU_PENALTIES and TPU_SPEC_TOKENS are mutually exclusive: "
                "penalties evolve within a step sequence, which breaks the "
                "parallel speculative verify"
            )
        # OpenAI top_logprobs alternatives: a compile choice — the per-
        # step [slots, vocab] top_k only exists in the program when >0.
        self.top_logprobs = max(0, top_logprobs)
        if self.top_logprobs and spec_tokens > 0:
            raise ValueError(
                "TPU_TOP_LOGPROBS and TPU_SPEC_TOKENS are mutually "
                "exclusive (the verify step has no per-emission "
                "alternatives plane)"
            )
        self.tokenizer = tokenizer
        # GSPMD-sharded serving (TPU_TP): a caller may hand a pre-built
        # mesh (dryruns, tests composing tp×cp), or just a tp degree —
        # then the engine owns its topology, carving a {"tp": tp} mesh
        # from ``devices`` (the replica-pool pod layout: dp across
        # replicas, tp within each) or the process device list. The
        # shard-init window (mesh build + param sharding + sharded
        # quantization) is timed and emitted as a ``tpu.shard_init``
        # span so slow boots are attributable.
        shard_t0 = time.time_ns()
        if mesh is None and int(tp or 0) > 1:
            from gofr_tpu.parallel.mesh import make_mesh

            mesh = make_mesh({"tp": int(tp)}, devices=devices)
        self.mesh = mesh  # multi-chip: NamedSharding placement over ICI
        from gofr_tpu.parallel.mesh import mesh_axis_sizes

        self.tp = (
            mesh_axis_sizes(mesh).get("tp", 1) if mesh is not None else 1
        )

        t0 = time.time()
        self.quant = ""
        if params is not None:
            # Pre-built params (e.g. a real-weights checkpoint loaded via
            # serving/hf_loader, possibly already int8/int4).
            from gofr_tpu.serving.hf_loader import params_quant_mode

            self.params = params
            self.quant = params_quant_mode(params)
        elif mesh is not None and self.family == "llm":
            # Sharded init: params materialize directly onto the mesh with
            # their Megatron-style partition specs — never gathered on one
            # chip (an 8B model doesn't fit one v5e).
            from gofr_tpu.models.transformer import transformer_param_specs
            from gofr_tpu.parallel.sharding import named_shardings, prune_specs

            shardings = named_shardings(
                prune_specs(transformer_param_specs(self.cfg), mesh), mesh
            )
            self.params = jax.jit(
                lambda k: self.spec.init(k, self.cfg), out_shardings=shardings
            )(jax.random.PRNGKey(seed))
        elif (quant or "").lower() in ("int8", "int4") and self.family == "llm":
            # Init DIRECTLY quantized, leaf by leaf: peak HBM is the
            # quantized tree plus one bf16 leaf — llama-3-8b's full bf16
            # tree (~16GB) would not fit a single v5e (VERDICT r1 #4).
            self.quant = (quant or "").lower()
            self.params = self._init_llm_quantized(seed)
        else:
            self.params = self.spec.init(jax.random.PRNGKey(seed), self.cfg)

        if quant and not self.quant:
            self.apply_quantization(quant)

        if mesh is not None:
            # Mesh topology observability: the per-axis device gauge
            # (dashboards show pod shape per model) and the completed
            # shard-init span covering mesh build + param sharding.
            from gofr_tpu.serving.observability import emit_boot_span

            if metrics is not None:
                for axis, size in mesh_axis_sizes(mesh).items():
                    metrics.set_gauge(
                        "app_tpu_mesh_devices", size,
                        "model", model_name, "axis", axis,
                    )
            emit_boot_span(
                "tpu.shard_init", shard_t0, time.time_ns(),
                attributes={
                    "tpu.model": model_name,
                    "tpu.mesh_axes": ",".join(
                        f"{a}={n}" for a, n in mesh_axis_sizes(mesh).items()
                    ),
                    "tpu.mesh_devices": int(mesh.devices.size),
                },
            )
        elif metrics is not None:
            # Unsharded engines advertise tp=1 so the gauge is uniform
            # across a mixed fleet.
            metrics.set_gauge(
                "app_tpu_mesh_devices", 1, "model", model_name,
                "axis", "tp",
            )

        if logger is not None:
            from gofr_tpu.models.transformer import count_params

            n_params = count_params(self.params)
            logger.infof(
                "model %s initialised: %.2fB params in %.1fs",
                model_name, n_params / 1e9, time.time() - t0,
            )

        self._seed = seed
        self._key = jax.random.PRNGKey(seed + 1)
        self._running = False
        self._draining = False  # graceful stop: reject new, finish live
        self._sched_idle = False  # published by the scheduler, read by drain
        self._fatal: Optional[BaseException] = None  # scheduler death reason
        # Serializes submission against the scheduler's final drain, so a
        # request can never be enqueued after the drain has already run.
        self._submit_lock = lockcheck.make_lock("InferenceEngine._submit_lock")
        self._drained = False
        # Supervision (serving/supervisor.py): the attached supervisor (if
        # any) owns the restart policy; the scheduler epoch brands each
        # scheduler thread so one abandoned mid-wedge can never drain or
        # dispatch against a restarted engine's state; salvaged retryable
        # requests park in _replay until the supervisor requeues them.
        self._supervisor: Optional[Any] = None
        self._epoch = 0
        self._replay: list[_GenRequest] = []
        self._restart_pending = False  # supervisor teardown in progress
        # Replica-tier failover (service/replica_pool.py): when this
        # engine is one replica of a pool, the pool installs a handoff —
        # terminal failure paths offer still-retryable requests to it
        # (the pool requeues them on another replica) before failing
        # them. None outside a pool: failures stay terminal.
        self._handoff: Optional[Any] = None
        # Disaggregated prefill/decode tier (TPU_REPLICA_ROLES): the
        # pool stamps this engine's role and, for prefill-tier
        # replicas, installs an exporter — the scheduler offers it
        # every just-finalized prefill (request + extracted KV-block
        # payload) instead of decoding locally; the pool ships the
        # blocks to a decode replica. "fused" (the default) serves both
        # phases locally, exactly as before this tier existed.
        self.tier_role: str = "fused"
        self._tier_exporter: Optional[Any] = None
        # Sampled-stream replay policy (TPU_REPLAY_EXACT): True (default)
        # regenerates the delivered prefix through the decode path —
        # byte-identical continuation at the cost of re-decoding it;
        # False re-prefills prompt + delivered tokens and restores the
        # sampling COUNTER (the noff plane) — one prefill pass, same
        # sample path, but prefill-kernel bf16 K/V rounding may flip a
        # later token. Greedy replays always take the fast path.
        self.replay_exact = bool(replay_exact)
        # Health state machine (SERVING → DEGRADED → RESTARTING → DOWN),
        # surfaced via health_check / both gRPC Health RPCs and the
        # app_tpu_engine_state gauge. DOWN until start_sync.
        self._state = "DOWN"
        # Set by the scheduler when it publishes "verifiably idle" and on
        # exit; the graceful drain clears it (under the submit lock)
        # before waiting, so a stale set from an earlier idle period
        # cannot satisfy a new drain. It is a drain wake-up only — while
        # the engine is busy it may still be set from before.
        self._idle_evt = threading.Event()
        # Admission control: token-budget accounting over the submit
        # queue (guarded by the submit lock like every other admission
        # flag) plus a throughput estimate for projected-wait shedding.
        self.queue_max = max(1, queue_max)
        self.queue_max_tokens = max(0, queue_max_tokens)
        # Per-SLO-class priority dequeue (TPU_QUEUE_CLASS_PROMOTE_S):
        # interactive pops ahead of queued standard/batch work, with
        # the promotion window as the starvation bound. 0 = strict
        # FIFO, the pre-class order.
        self.class_promote_s = max(0.0, class_promote_s)
        self._queued_tokens = 0
        self._expected_tps = max(0.0, expected_tps)
        # Sliding-window AGGREGATE tokens/sec across the whole batch —
        # the shedding denominator. (The previous per-request EWMA
        # underestimated batched throughput by ~the batch size and shed
        # correspondingly too eagerly.)
        self._tput = AggregateThroughput()
        # Per-tenant admission quota (TPU_TENANT_QUEUE_MAX): queued
        # request count per X-Tenant-Id, guarded by the submit lock.
        self.tenant_queue_max = max(0, tenant_queue_max)
        self._tenant_queued: dict[str, int] = {}
        # Watchdog: latched unhealthy reason, reported by health_check
        # and set (under the submit lock) by the trip callback.
        self._unhealthy_reason: Optional[str] = None
        self._watchdog: Optional[Watchdog] = None
        if watchdog_s > 0:
            self._watchdog = Watchdog(
                watchdog_s,
                on_trip=self._on_watchdog_trip,
                metrics=metrics,
                logger=logger,
                model_name=model_name,
            )

        # Request-lifecycle observability (serving/observability.py):
        # the hub mints per-request timelines, owns the flight recorder,
        # and summarizes phases into histograms/spans at retirement. It
        # deliberately lives OUTSIDE _init_llm_serving_state so the
        # recorder's history survives supervisor warm restarts (the
        # replay/failover annotations are exactly what an operator wants
        # to see after one). TPU_FLIGHT_RECORDER=0 disables the ring —
        # the bench overhead A/B knob.
        if flight_recorder is None:
            flight_recorder = os.environ.get(
                "TPU_FLIGHT_RECORDER", "1"
            ).lower() not in ("0", "false", "no")
        from gofr_tpu.serving.observability import (
            FlightRecorder,
            RequestObservability,
        )

        self._obs = RequestObservability(
            model_name,
            metrics=metrics,
            recorder=(
                FlightRecorder(
                    capacity=max(1, flight_records),
                    slow_s=flight_slow_s,
                )
                if flight_recorder else None
            ),
        )

        # Tenant attribution + SLO burn rates (serving/tenant_ledger.py
        # + serving/slo.py; docs/advanced-guide/observability.md "Tenant
        # attribution & SLOs"). Like the flight recorder, both live
        # OUTSIDE _init_llm_serving_state so attribution and burn state
        # survive supervisor warm restarts. TPU_TENANT_LEDGER=0 removes
        # the whole attribution layer — every scheduler hook degrades to
        # one `is not None`.
        if tenant_ledger is None:
            tenant_ledger = os.environ.get(
                "TPU_TENANT_LEDGER", "1"
            ).lower() not in ("0", "false", "no")
        from gofr_tpu.serving.tenant_ledger import TenantLedger

        self._tenant_ledger: Optional[TenantLedger] = (
            TenantLedger(
                model_name,
                metrics=metrics,
                label_max=tenant_label_max,
                table_max=tenant_table_max,
            )
            if tenant_ledger else None
        )
        # Fairness-aware shedding (TPU_TENANT_FAIR_SHARE, off by
        # default): the fraction of the queue budget one tenant may
        # hold before admission sheds IT (429 reason=tenant_fair_share)
        # instead of letting its burst exhaust the global budget for
        # everyone. Needs the ledger (the share denominator).
        self.tenant_fair_share = max(0.0, min(1.0, tenant_fair_share))
        from gofr_tpu.serving.slo import SLOEngine

        # Control-plane master switch, resolved HERE because the
        # SLOEngine below needs to know whether to auto-track per-tenant
        # burn rings (the per-tenant brownout loop's signal). Off
        # (TPU_CONTROL_PLANE=0) builds nothing: no tracking, no
        # controller, every hook one `is not None`.
        if control_plane is None:
            control_plane = os.environ.get(
                "TPU_CONTROL_PLANE", "1"
            ).lower() not in ("0", "false", "no")
        self._slo: Optional[SLOEngine] = None
        if (
            slo_ttft_ms > 0 or slo_e2e_ms > 0 or slo_availability > 0
            or slo_tenant_objectives
        ):
            self._slo = SLOEngine(
                model_name,
                ttft_ms=slo_ttft_ms,
                e2e_ms=slo_e2e_ms,
                availability=slo_availability,
                tenant_objectives=slo_tenant_objectives,
                track_tenants=(
                    max(0, int(control_tenant_table))
                    if control_plane else 0
                ),
                metrics=metrics,
            )
        # The observability hub feeds every retired timeline's phases
        # into the burn-rate engine (and keeps minting timelines even
        # when recorder/metrics/exporter are all off, so SLOs alone
        # still see every request).
        self._obs.slo = self._slo
        # Closed-loop overload control (serving/brownout.py; docs/
        # advanced-guide/resilience.md "Brownout & overload control"):
        # the burn-rate-driven degradation ladder. Needs the SLOEngine
        # (the burn rate IS the control signal); TPU_BROWNOUT=0 builds
        # no controller — every hook degrades to one `is not None` and
        # today's behavior is byte-identical.
        from gofr_tpu.serving.brownout import (
            BrownoutController,
            normalize_slo_class,
            parse_tenant_class_map,
        )

        self._normalize_slo_class = normalize_slo_class
        self._tenant_class_map = parse_tenant_class_map(tenant_slo_class)
        if brownout is None:
            brownout = os.environ.get(
                "TPU_BROWNOUT", "1"
            ).lower() not in ("0", "false", "no")
        self._brownout: Optional[BrownoutController] = (
            BrownoutController(
                model_name,
                enter_burn=brownout_enter,
                exit_burn=brownout_exit,
                sustain_s=brownout_sustain_s,
                exit_sustain_s=brownout_exit_sustain_s,
                max_new_tokens=brownout_max_new,
                aimd_cut=brownout_aimd_cut,
                recover_per_s=brownout_recover_per_s,
                min_headroom=brownout_min_headroom,
                metrics=metrics,
                logger=logger,
            )
            if brownout and self._slo is not None else None
        )

        # Continuous scheduler-loop profiler (serving/loop_profiler.py;
        # docs/advanced-guide/observability.md "Scheduler-loop
        # signals"): per-phase wall-time attribution for every
        # scheduler pass, the loop-utilization / host-overhead-ratio
        # signals, and the hysteretic stall detector whose anomaly
        # records land on /debug/loop (optionally auto-capturing a
        # bounded device trace through the profiler_capture singleton).
        # Lives OUTSIDE _init_llm_serving_state like the flight
        # recorder — rolling stats and anomaly rings survive supervisor
        # warm restarts. TPU_LOOP_PROFILE=0 builds no profiler: every
        # scheduler hook degrades to one `is not None` and the loop is
        # byte-identical to the pre-profiler scheduler.
        if loop_profile is None:
            loop_profile = os.environ.get(
                "TPU_LOOP_PROFILE", "1"
            ).lower() not in ("0", "false", "no")
        self._loop_prof: Any = None
        if loop_profile and self.family == "llm":
            from gofr_tpu.serving.loop_profiler import LoopProfiler

            trace_capture = None
            if loop_trace_ms > 0:
                from gofr_tpu.serving.profiler_capture import get_capture

                trace_capture = get_capture(
                    cooldown_s=loop_trace_cooldown_s
                )
            self._loop_prof = LoopProfiler(
                model_name,
                stall_s=loop_stall_s,
                stall_factor=loop_stall_factor,
                anomaly_records=loop_anomalies,
                trace_ms=loop_trace_ms,
                capture=trace_capture,
                metrics=metrics,
                logger=logger,
            )
            self._loop_prof.context = self._loop_context

        # Device-resource observability (serving/device_telemetry.py):
        # the compile tracker wraps every jitted serving program built
        # below (so it must exist before the family branch), and the
        # HBM ledger is built with the serving state (its component
        # sizes are fixed per boot). The tracker captures the ambient
        # trace context HERE — warm-up compiles fire on the scheduler
        # thread, but their tpu.compile spans belong to the boot trace.
        from gofr_tpu.serving.device_telemetry import CompileTracker

        self._compiles = CompileTracker(
            model_name, metrics=metrics, logger=logger
        )
        if self._compile_cache_info is not None:
            # Wired at the very top of __init__ (must precede the first
            # jit); recorded here once the tracker exists.
            self._compiles.set_cache_info(self._compile_cache_info)
        if self._loop_prof is not None:
            # A pass during which XLA compiled is the compile tracker's
            # to attribute — the loop profiler's stall detector exempts
            # it (or every boot would open with a pinned anomaly).
            self._loop_prof.compiles = lambda: self._compiles.total
        self._ledger: Any = None
        # Saturation-aware control knobs (docs/advanced-guide/
        # observability.md "Device-resource signals"): the HBM-fraction
        # eviction watermark (TPU_PREFIX_EVICT_WM stays the explicit
        # override), admission's headroom floor, and the operator's
        # explicit per-device HBM budget for backends whose
        # memory_stats() reports nothing.
        self.prefix_evict_hbm_frac = max(0.0, prefix_evict_hbm_frac)
        self.admit_min_headroom = max(0.0, admit_min_headroom)
        self.hbm_budget_bytes = max(0, hbm_budget_bytes)
        self.effective_evict_watermark = 0
        # Prefix-hit-aware admission ordering (TPU_QUEUE_PREFIX_AWARE,
        # off by default): within one SLO class, pop requests with a
        # known radix-prefix hit first. Read by
        # _init_llm_serving_state's queue build (survives warm restart).
        self.queue_prefix_aware = bool(queue_prefix_aware)

        if self.family == "llm":
            self.max_len = min(max_len, self.cfg.max_len)
            self.n_slots = n_slots
            self.window_k = max(1, window_k)
            self.pipeline_depth = max(1, pipeline_depth)
            # Mega-windows (throughput mode): ONE dispatch runs up to
            # `mega_windows` k-step windows inside a device-side
            # lax.while_loop that early-exits when every slot's remaining
            # budget is covered (or its EOS was emitted). Through a
            # network-attached relay each dispatch costs a full host↔device
            # RTT *in the calling thread*, so at window 8 the RTT is paid
            # every 8 steps (~72 of each ~105 ms wall, measured — r3
            # campaign); one mega dispatch amortizes it over m×k steps.
            # Trade-off: tokens surface per mega-window, not per window —
            # streaming granularity coarsens, so serving defaults keep it
            # off and bursty/offline throughput turns it on.
            self.mega_windows = max(0, mega_windows)
            # Chunked prefill: ONE fixed [prefill_batch, prefill_chunk]
            # compile serves every prompt length, and chunk steps interleave
            # with decode windows so admission never stalls active streams.
            self.prefill_chunk = max(16, min(prefill_chunk, self.max_len))
            self.prefill_batch = max(1, min(prefill_batch, n_slots))
            # Multi-chunk prefill (long-prompt dispatch amortizer): when
            # every prefilling row has ≥2 full chunks left before its
            # finalize chunk, run up to this many chunks per dispatch in
            # a device-side loop. 1 disables (every chunk is its own
            # dispatch — the latency-interleaving default).
            self.prefill_depth = max(1, prefill_depth)
            self.truncate_prompts = truncate_prompts
            # Speculative decoding (n-gram prompt lookup): each device step
            # verifies spec_tokens drafts + 1, so windows can emit up to
            # window_k * (spec_tokens+1) tokens per slot.
            self.spec_tokens = max(0, spec_tokens)
            step_tokens = self.window_k * (self.spec_tokens + 1)
            reserve = 1 + (self.pipeline_depth + 1) * step_tokens
            if self.max_len <= reserve:
                raise ValueError(
                    f"max_len={self.max_len} too small: need > {reserve} "
                    f"(1 + (pipeline_depth+1)*window_k*(spec_tokens+1)) so "
                    f"admission can reserve pipelined-window overshoot "
                    f"room; lower window_k/pipeline_depth/spec_tokens or "
                    f"raise max_len"
                )
            self.kv_quant = (kv_quant or "").lower()
            # Paged KV (TPU_KV_BLOCK>0): block-pool cache + host allocator
            # — HBM scales with resident tokens, not slots × max_len.
            self.kv_block = max(0, kv_block)
            self.kv_pool_blocks = kv_pool_blocks
            self.prefix_slots = max(0, prefix_slots)
            # Automatic block-level prefix caching (TPU_AUTO_PREFIX):
            # retired prompts' full KV blocks stay indexed in a radix
            # trie and later requests admission-alias them into their
            # block table — zero-copy hits, refcounted sharing, COW'd
            # boundary (serving/radix_cache.py + docs/advanced-guide/
            # prefix-caching.md). Paged-cache only: sharing IS table
            # aliasing.
            self.auto_prefix = bool(auto_prefix)
            self.prefix_cache_blocks = max(0, prefix_cache_blocks)
            # Prefix-cache eviction watermark (TPU_PREFIX_EVICT_WM):
            # keep at least this many pool blocks FREE by sweeping LRU
            # radix entries from the scheduler loop, so admission under
            # pressure stops paying the synchronous pre-evict cost
            # inside its own grow. 0 = off (evict only on shortfall).
            self.prefix_evict_watermark = max(0, prefix_evict_watermark)
            if self.auto_prefix and not self.kv_block:
                raise ValueError(
                    "TPU_AUTO_PREFIX requires the paged KV cache "
                    "(TPU_KV_BLOCK > 0): prefix hits alias pool blocks "
                    "through the block table"
                )
            if self.kv_block:
                if self.max_len % self.kv_block:
                    raise ValueError(
                        f"max_len={self.max_len} must be a multiple of "
                        f"kv_block={self.kv_block}"
                    )
                if prefix_slots > 0:
                    raise ValueError(
                        "prefix-KV reuse and the paged cache are mutually "
                        "exclusive (the pool copies slot rows; use "
                        "TPU_AUTO_PREFIX for paged prefix sharing)"
                    )
            # Prefix-cache observability counters (host-side mirrors of
            # app_tpu_prefix_{lookup,hit_tokens}_total so bench/tests
            # read them without a metrics manager). Cumulative across
            # warm restarts — the INDEX resets with the cache planes,
            # these do not.
            self._prefix_lookups = 0
            self._prefix_hit_tokens = 0
            self._prefill_chunk_steps = 0
            self._sched: Optional[threading.Thread] = None
            # Host→device uploads: on a mesh, place as a REPLICATED global
            # array — on a multi-host (DCN) mesh a bare jnp.asarray would
            # make a process-local array that cannot feed the global-SPMD
            # jits (every process runs this same code with the same host
            # values, so replicated placement is well-defined).
            if mesh is not None:
                from jax.sharding import (
                    NamedSharding as _NS,
                    PartitionSpec as _P,
                )

                _rep = _NS(mesh, _P())
                self._up = lambda x: jax.device_put(x, _rep)  # noqa: E731
            else:
                self._up = jnp.asarray
            # Multi-PROCESS mesh on a non-TPU backend: serialize device
            # programs. A real TPU core executes one program at a time, so
            # identical per-process launch order is enough for its
            # collectives to pair up; the CPU backend's gloo collectives
            # run on a thread pool, and two in-flight programs (pipelined
            # windows, prefill overlapping decode) interleave their
            # collectives nondeterministically across ranks — observed as
            # gloo "Received data size doesn't match expected size".
            self._lockstep = False
            multiproc = False
            if mesh is not None:
                procs = {d.process_index for d in mesh.devices.flat}
                multiproc = len(procs) > 1
                self._lockstep = (
                    multiproc and jax.default_backend() != "tpu"
                )
            # Host-side default-seed source for requests without one: each
            # unseeded request gets a fresh draw (OpenAI semantics), while
            # an explicit seed reproduces exactly. Single-process engines
            # mix in boot entropy so restarts/replicas don't replay; a
            # multi-PROCESS mesh keeps the engine-seed-derived stream —
            # every rank must draw IDENTICAL defaults or the SPMD
            # schedulers diverge (set distinct TPU seeds per replica
            # group for cross-replica variety).
            import random as _random

            self._seed_rng = (
                _random.Random(seed + 3) if multiproc
                else _random.Random(os.urandom(16))
            )
            # Multi-LoRA serving: merge zeroed stacked adapter leaves
            # into params["layers"] (slot 0 = base; load_lora fills
            # slots 1..lora_slots). A COMPILE choice: engines without
            # TPU_LORA_SLOTS carry no adapter gather/einsums at all.
            self.lora_slots = max(0, lora_slots)
            self.lora_rank = max(1, lora_rank)
            self._lora_targets = tuple(
                t.strip() for t in lora_targets.split(",") if t.strip()
            )
            self._lora_names: dict[str, int] = {}
            # Per-adapter-slot load generation: bumped by every load/
            # unload so in-flight prefix registrations against an old
            # generation can be detected and dropped.
            self._lora_gen = [0] * (self.lora_slots + 1)
            if self.lora_slots:
                from gofr_tpu.models.transformer import (
                    init_lora,
                    lora_param_specs,
                )

                leaves = init_lora(
                    self.cfg, 1 + self.lora_slots, self.lora_rank,
                    self._lora_targets,
                )
                if mesh is not None:
                    from gofr_tpu.parallel.sharding import (
                        named_shardings,
                        prune_specs,
                    )

                    lspecs = prune_specs(
                        lora_param_specs(self._lora_targets), mesh
                    )
                    leaves = {
                        k: jax.device_put(
                            v, named_shardings(lspecs[k], mesh)
                        )
                        for k, v in leaves.items()
                    }
                self.params = {
                    **self.params,
                    "layers": {**self.params["layers"], **leaves},
                }
            # Per-boot serving state (KV cache, allocator, queues, device
            # planes) lives in its own method so the supervisor's warm
            # restart can rebuild it without re-initializing params or
            # recompiling programs.
            self._init_llm_serving_state()
            self._build_llm_steps()
        elif self.family == "encoder":
            self.max_len = min(max_len, self.cfg.max_len)
            self._build_encoder_step()
            self._batcher = DynamicBatcher(
                self._execute_embed, max_batch=max_batch, max_wait_s=max_wait_s,
                metrics=metrics, name="embed",
            )
        elif self.family == "vision":
            self._build_vision_step()
            self._batcher = DynamicBatcher(
                self._execute_classify, max_batch=max_batch, max_wait_s=max_wait_s,
                metrics=metrics, name="classify",
            )
        elif self.family == "seq2seq":
            self.max_len = min(max_len, self.cfg.max_len)
            self._build_seq2seq_step()
            self._batcher = DynamicBatcher(
                self._execute_seq2seq, max_batch=max_batch,
                max_wait_s=max_wait_s, metrics=metrics, name="seq2seq",
            )
        else:
            raise ValueError(f"unknown model family {self.family}")
        if self.family != "llm":
            # Non-LLM families have no serving-state rebuild seam: the
            # ledger (params + batcher workspace is negligible) builds
            # once here.
            self._build_hbm_ledger()
        # The fault-tolerant control plane (serving/control_plane.py;
        # docs/advanced-guide/resilience.md "Control plane"): built
        # LAST so its signal closures capture sensors that only exist
        # after _init_llm_serving_state (queue, throughput meter, HBM
        # ledger). LLM-family only — every loop it closes is a
        # scheduler-loop loop. TPU_CONTROL_PLANE=0 builds nothing.
        self._control: Any = None
        if control_plane and self.family == "llm":
            from gofr_tpu.serving.control_plane import ControlPlane

            cp = ControlPlane(
                model_name,
                stale_s=control_stale_s,
                tenant_enter=control_tenant_enter,
                tenant_exit=control_tenant_exit,
                tenant_sustain_s=control_tenant_sustain_s,
                tenant_exit_sustain_s=control_tenant_exit_sustain_s,
                tenant_max_new=control_tenant_max_new,
                tenant_aimd_cut=control_tenant_aimd_cut,
                tenant_recover_per_s=control_tenant_recover_per_s,
                tenant_table_max=control_tenant_table,
                host_ratio=control_host_ratio,
                host_util=control_host_util,
                host_sustain_s=control_host_sustain_s,
                predict_window_s=control_predict_window_s,
                predict_horizon_s=control_predict_horizon_s,
                # The predictive threshold defaults to half the queue
                # bound: fire while the reactive sustained-threshold
                # path still has runway.
                predict_depth=(
                    float(control_predict_depth)
                    if control_predict_depth > 0
                    else max(1.0, 0.5 * float(self.queue_max))
                ),
                predict_hold_s=control_predict_hold_s,
                metrics=metrics,
                logger=logger,
                clock=self._obs.now,
            )
            slo = self._slo
            if slo is not None:
                cp.register(
                    "tenant_burn",
                    lambda: slo.tenant_burns("5m"),
                    kind="map",
                )
            prof = self._loop_prof
            if prof is not None:
                cp.register("host_overhead_ratio", prof.host_overhead_ratio)
                cp.register("loop_utilization", prof.utilization)
            cp.register(
                "queue_depth", lambda: float(self._pending.qsize())
            )
            cp.register(
                "throughput",
                lambda: float(self._tput.rate(self._obs.now())),
            )
            if self._ledger is not None:
                cp.register(
                    "hbm_headroom",
                    lambda: float(self.hbm_headroom_ratio()),
                )
            self._control = cp

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------

    @classmethod
    def from_config(
        cls,
        config: Any,
        logger: Any = None,
        metrics: Any = None,
        devices: Any = None,
    ) -> "InferenceEngine":
        """Container seam: all knobs are TPU_* env keys (the datasource
        config idiom, reference ``sql/sql.go:109-118``).

        ``TPU_TP=N`` serves tensor-parallel over N chips (ICI): params
        Megatron-sharded, the (paged) KV pool's head axis sharded, XLA
        inserts the collectives. (``TPU_MESH_TP`` is the historical
        alias.) Data-parallel serving scale-out is engine replicas
        behind the service tier — with ``TPU_REPLICAS > 1`` each
        in-proc replica becomes one sharded pod carved from a disjoint
        ``devices`` slice (dp across replicas, tp within; see
        ``serving/backend.py``).
        """
        from gofr_tpu.serving.slo import tenant_objectives_from_config

        mesh = None
        tp = int(
            config.get_or_default(
                "TPU_TP", config.get_or_default("TPU_MESH_TP", "1")
            )
        )
        # Serving context parallelism: the KV cache's length axis shards
        # over cp chips, so max_len can exceed one chip's cache HBM
        # (GSPMD turns the sharded softmax reductions into collectives).
        cp = int(config.get_or_default("TPU_MESH_CP", "1"))
        if tp > 1 or cp > 1:
            from gofr_tpu.parallel import make_mesh

            axes = {}
            if tp > 1:
                axes["tp"] = tp
            if cp > 1:
                axes["cp"] = cp
            mesh = make_mesh(axes, devices=devices)
        model_name = config.get_or_default("TPU_MODEL", "llama-tiny")
        ckpt = config.get_or_default("TPU_CHECKPOINT", "")
        quant_cfg = config.get_or_default("TPU_QUANT", "")
        # Speculative decoding defaults ON where the bench gate holds
        # (see resolve_spec_tokens): resolve before the constructor so
        # an implicit default can yield to explicitly-enabled features
        # instead of raising the constructor's explicit-conflict error.
        top_logprobs_cfg = int(
            config.get_or_default("TPU_TOP_LOGPROBS", "0")
        )
        penalties_cfg = config.get_or_default(
            "TPU_PENALTIES", "false"
        ).lower() in ("1", "true", "yes")
        try:
            import jax as _jax

            backend = _jax.default_backend()
        except Exception:  # noqa: BLE001 — backend probe only steers a default
            backend = "cpu"
        spec_tokens_cfg, spec_note = resolve_spec_tokens(
            config.get_or_default("TPU_SPEC_TOKENS", "auto"),
            backend, penalties_cfg, top_logprobs_cfg,
        )
        if spec_note and logger is not None:
            logger.infof("%s", spec_note)
        params = None
        if ckpt:
            from gofr_tpu.serving.hf_loader import (
                is_hf_checkpoint,
                load_hf_llama,
            )

            if is_hf_checkpoint(ckpt):
                # Real weights (HF safetensors layout), quantized leaf-wise
                # on device as they land — the bf16 tree never fully
                # materializes (VERDICT r1 #5 + #4) — and placed straight
                # onto the tp mesh when one is configured.
                from gofr_tpu.models.registry import get_model

                spec = get_model(model_name)
                if spec.family == "seq2seq":
                    from gofr_tpu.models.t5 import load_hf_t5

                    if mesh is not None:
                        # Silently serving replicated would defeat the
                        # operator's explicit parallelism settings.
                        raise ValueError(
                            "TPU_MESH_* is not supported for seq2seq "
                            "checkpoints yet"
                        )
                    params = load_hf_t5(
                        ckpt, spec.config, quant=quant_cfg
                    )
                else:
                    params = load_hf_llama(
                        ckpt, spec.config, quant=quant_cfg,
                        mesh=mesh, logger=logger,
                    )
        engine = cls(
            model_name,
            mesh=mesh,
            params=params,
            quant="" if (params is not None or ckpt) else quant_cfg,
            n_slots=int(config.get_or_default("TPU_KV_SLOTS", "8")),
            max_len=int(config.get_or_default("TPU_MAX_LEN", "1024")),
            max_batch=int(config.get_or_default("TPU_MAX_BATCH", "8")),
            max_wait_s=float(config.get_or_default("TPU_BATCH_WAIT_MS", "5")) / 1e3,
            window_k=int(config.get_or_default("TPU_DECODE_WINDOW", "8")),
            pipeline_depth=int(config.get_or_default("TPU_PIPELINE_DEPTH", "2")),
            mega_windows=int(config.get_or_default("TPU_MEGA_WINDOWS", "0")),
            prefill_depth=int(config.get_or_default("TPU_PREFILL_DEPTH", "1")),
            kv_quant=config.get_or_default("TPU_KV_QUANT", ""),
            prefix_slots=int(config.get_or_default("TPU_PREFIX_SLOTS", "0")),
            prefill_chunk=int(config.get_or_default("TPU_PREFILL_CHUNK", "256")),
            prefill_batch=int(config.get_or_default("TPU_PREFILL_BATCH", "8")),
            truncate_prompts=config.get_or_default(
                "TPU_TRUNCATE_PROMPTS", "false"
            ).lower() in ("1", "true", "yes"),
            top_k=int(config.get_or_default("TPU_TOP_K", "0")),
            top_logprobs=top_logprobs_cfg,
            enable_top_p=config.get_or_default("TPU_TOP_P", "false").lower()
            in ("1", "true", "yes"),
            enable_penalties=penalties_cfg,
            spec_tokens=spec_tokens_cfg,
            kv_block=int(config.get_or_default("TPU_KV_BLOCK", "0")),
            lora_slots=int(config.get_or_default("TPU_LORA_SLOTS", "0")),
            lora_rank=int(config.get_or_default("TPU_LORA_RANK", "16")),
            lora_targets=config.get_or_default(
                "TPU_LORA_TARGETS", "wq,wk,wv,wo"
            ),
            kv_pool_blocks=int(
                config.get_or_default("TPU_KV_POOL_BLOCKS", "0")
            ),
            # Automatic block-level prefix caching (needs TPU_KV_BLOCK).
            auto_prefix=config.get_or_default(
                "TPU_AUTO_PREFIX", "false"
            ).lower() in ("1", "true", "yes"),
            prefix_cache_blocks=int(
                config.get_or_default("TPU_PREFIX_CACHE_BLOCKS", "0")
            ),
            # Free-block watermark for proactive radix-cache eviction
            # (blocks; 0 = evict only on allocation shortfall).
            prefix_evict_watermark=int(
                config.get_or_default("TPU_PREFIX_EVICT_WM", "0")
            ),
            # Device-resource observability knobs (docs/advanced-guide/
            # observability.md "Device-resource signals"): derive the
            # eviction watermark from HBM headroom instead of a raw
            # block count (the explicit TPU_PREFIX_EVICT_WM wins when
            # both are set), shed admissions below a headroom floor,
            # and state the per-device HBM budget on backends whose
            # memory_stats() reports nothing.
            prefix_evict_hbm_frac=float(
                config.get_or_default("TPU_PREFIX_EVICT_HBM_FRAC", "0")
            ),
            admit_min_headroom=float(
                config.get_or_default("TPU_ADMIT_MIN_HEADROOM", "0")
            ),
            hbm_budget_bytes=int(
                config.get_or_default("TPU_HBM_BYTES", "0")
            ),
            # Request-lifecycle resilience knobs (docs/advanced-guide/
            # resilience.md): bounded submit queue + token budget,
            # throughput prior for projected-wait shedding, and the
            # scheduler watchdog's wall-time bound (0 = disabled).
            queue_max=int(config.get_or_default("TPU_QUEUE_MAX", "1024")),
            queue_max_tokens=int(
                config.get_or_default("TPU_QUEUE_TOKENS", "0")
            ),
            class_promote_s=float(
                config.get_or_default("TPU_QUEUE_CLASS_PROMOTE_S", "5")
            ),
            tenant_queue_max=int(
                config.get_or_default("TPU_TENANT_QUEUE_MAX", "0")
            ),
            # Tenant attribution + SLO layer (docs/advanced-guide/
            # observability.md "Tenant attribution & SLOs"): the ledger
            # master switch (0 = zero scheduler-hook overhead), the
            # metric-label cardinality clamp, the fairness-shed share
            # (0 = off), the declarative objectives, and the persistent
            # XLA compile-cache directory.
            tenant_ledger=config.get_or_default(
                "TPU_TENANT_LEDGER", "1"
            ).lower() not in ("0", "false", "no"),
            tenant_label_max=int(
                config.get_or_default("TPU_TENANT_LABEL_MAX", "8")
            ),
            tenant_table_max=int(
                config.get_or_default("TPU_TENANT_TABLE_MAX", "256")
            ),
            tenant_fair_share=float(
                config.get_or_default("TPU_TENANT_FAIR_SHARE", "0")
            ),
            slo_ttft_ms=float(
                config.get_or_default("TPU_SLO_TTFT_MS", "0")
            ),
            slo_e2e_ms=float(
                config.get_or_default("TPU_SLO_E2E_MS", "0")
            ),
            slo_availability=float(
                config.get_or_default("TPU_SLO_AVAILABILITY", "0")
            ),
            # Per-tenant SLO overrides (TPU_SLO_TENANT_<NAME>_TTFT_MS
            # and kin) and the brownout ladder (docs/advanced-guide/
            # resilience.md "Brownout & overload control"): thresholds
            # on the 5m burn with sustain windows for hysteresis, the
            # L1 generation clamp, the L2 AIMD parameters, and the
            # optional headroom floor that also counts as pressure.
            slo_tenant_objectives=tenant_objectives_from_config(config),
            brownout=config.get_or_default(
                "TPU_BROWNOUT", "1"
            ).lower() not in ("0", "false", "no"),
            brownout_enter=float(
                config.get_or_default("TPU_BROWNOUT_ENTER", "2")
            ),
            brownout_exit=float(
                config.get_or_default("TPU_BROWNOUT_EXIT", "1")
            ),
            brownout_sustain_s=float(
                config.get_or_default("TPU_BROWNOUT_SUSTAIN_S", "10")
            ),
            brownout_exit_sustain_s=float(
                config.get_or_default("TPU_BROWNOUT_EXIT_SUSTAIN_S", "30")
            ),
            brownout_max_new=int(
                config.get_or_default("TPU_BROWNOUT_MAX_NEW", "256")
            ),
            brownout_aimd_cut=float(
                config.get_or_default("TPU_BROWNOUT_AIMD_CUT", "0.5")
            ),
            brownout_recover_per_s=float(
                config.get_or_default("TPU_BROWNOUT_RECOVER_PER_S", "0.02")
            ),
            brownout_min_headroom=float(
                config.get_or_default("TPU_BROWNOUT_MIN_HEADROOM", "0")
            ),
            # The fault-tolerant control plane (docs/advanced-guide/
            # resilience.md "Control plane"): the master switch, the
            # signal staleness window, the per-tenant brownout ladder's
            # thresholds/AIMD, the host-overhead pressure loop, and the
            # predictive-scaling trend fit.
            control_plane=config.get_or_default(
                "TPU_CONTROL_PLANE", "1"
            ).lower() not in ("0", "false", "no"),
            control_stale_s=float(
                config.get_or_default("TPU_CONTROL_STALE_S", "10")
            ),
            control_tenant_enter=float(
                config.get_or_default("TPU_CONTROL_TENANT_ENTER", "2")
            ),
            control_tenant_exit=float(
                config.get_or_default("TPU_CONTROL_TENANT_EXIT", "1")
            ),
            control_tenant_sustain_s=float(
                config.get_or_default("TPU_CONTROL_TENANT_SUSTAIN_S", "10")
            ),
            control_tenant_exit_sustain_s=float(
                config.get_or_default(
                    "TPU_CONTROL_TENANT_EXIT_SUSTAIN_S", "30"
                )
            ),
            control_tenant_max_new=int(
                config.get_or_default("TPU_CONTROL_TENANT_MAX_NEW", "256")
            ),
            control_tenant_aimd_cut=float(
                config.get_or_default("TPU_CONTROL_TENANT_AIMD_CUT", "0.5")
            ),
            control_tenant_recover_per_s=float(
                config.get_or_default(
                    "TPU_CONTROL_TENANT_RECOVER_PER_S", "0.02"
                )
            ),
            control_tenant_table=int(
                config.get_or_default("TPU_CONTROL_TENANT_TABLE", "64")
            ),
            control_host_ratio=float(
                config.get_or_default("TPU_CONTROL_HOST_RATIO", "0.85")
            ),
            control_host_util=float(
                config.get_or_default("TPU_CONTROL_HOST_UTIL", "0.75")
            ),
            control_host_sustain_s=float(
                config.get_or_default("TPU_CONTROL_HOST_SUSTAIN_S", "30")
            ),
            control_predict_window_s=float(
                config.get_or_default("TPU_CONTROL_PREDICT_WINDOW_S", "60")
            ),
            control_predict_horizon_s=float(
                config.get_or_default(
                    "TPU_CONTROL_PREDICT_HORIZON_S", "30"
                )
            ),
            control_predict_depth=float(
                config.get_or_default("TPU_CONTROL_PREDICT_DEPTH", "0")
            ),
            control_predict_hold_s=float(
                config.get_or_default("TPU_CONTROL_PREDICT_HOLD_S", "30")
            ),
            # Prefix-hit-aware admission ordering (off by default —
            # byte-identical pop order when off).
            queue_prefix_aware=config.get_or_default(
                "TPU_QUEUE_PREFIX_AWARE", "0"
            ).lower() not in ("", "0", "false", "no"),
            tenant_slo_class=config.get_or_default(
                "TPU_TENANT_SLO_CLASS", ""
            ),
            compile_cache_dir=config.get_or_default(
                "TPU_COMPILE_CACHE_DIR", ""
            ),
            expected_tps=float(
                config.get_or_default("TPU_EXPECTED_TPS", "0")
            ),
            watchdog_s=float(config.get_or_default("TPU_WATCHDOG_S", "0")),
            replay_exact=config.get_or_default(
                "TPU_REPLAY_EXACT", "true"
            ).lower() in ("1", "true", "yes"),
            # Observability (docs/advanced-guide/observability.md): the
            # flight recorder's ring size, slow-pin threshold, and the
            # master switch (0 = off, the bench overhead A/B).
            flight_recorder=config.get_or_default(
                "TPU_FLIGHT_RECORDER", "1"
            ).lower() not in ("0", "false", "no"),
            flight_records=int(
                config.get_or_default("TPU_FLIGHT_RECORDS", "256")
            ),
            flight_slow_s=float(
                config.get_or_default("TPU_FLIGHT_SLOW_S", "5")
            ),
            # Scheduler-loop profiler (docs/advanced-guide/
            # observability.md "Scheduler-loop signals"): per-phase
            # pass attribution + stall anomalies on /debug/loop. The
            # master switch (0 = byte-identical pre-profiler loop, the
            # bench overhead A/B), the absolute and p95-relative stall
            # bounds, the anomaly-ring size, and the optional
            # stall-triggered device-trace capture (ms; 0 = off) with
            # its storm cooldown.
            loop_profile=config.get_or_default(
                "TPU_LOOP_PROFILE", "1"
            ).lower() not in ("0", "false", "no"),
            loop_stall_s=float(
                config.get_or_default("TPU_LOOP_STALL_S", "1.0")
            ),
            loop_stall_factor=float(
                config.get_or_default("TPU_LOOP_STALL_FACTOR", "10")
            ),
            loop_anomalies=int(
                config.get_or_default("TPU_LOOP_ANOMALIES", "64")
            ),
            loop_trace_ms=int(
                config.get_or_default("TPU_LOOP_TRACE_MS", "0")
            ),
            loop_trace_cooldown_s=float(
                config.get_or_default("TPU_LOOP_TRACE_COOLDOWN_S", "60")
            ),
            logger=logger,
            metrics=metrics,
            tokenizer=tokenizer_from_config(config, logger),
        )
        if ckpt and params is None:
            # Orbax checkpoint path: restore bf16 params, then quantize.
            from gofr_tpu.serving.checkpoint import maybe_restore_params

            engine.params = maybe_restore_params(config, engine.params, logger)
            engine.apply_quantization(quant_cfg)
        # Boot-time LoRA adapters: TPU_LORA_ADAPTERS="name=path,name2=p2"
        # (HF PEFT checkpoint dirs). More can load at runtime via
        # engine.load_lora.
        adapters_cfg = config.get_or_default("TPU_LORA_ADAPTERS", "")
        if adapters_cfg:
            for entry in adapters_cfg.replace(";", ",").split(","):
                entry = entry.strip()
                if not entry:
                    continue
                if "=" not in entry:
                    raise ValueError(
                        f"TPU_LORA_ADAPTERS entry {entry!r} is not "
                        f"name=path"
                    )
                name, path = entry.split("=", 1)
                engine.load_lora(name.strip(), path.strip())
        # Self-healing (docs/advanced-guide/resilience.md): TPU_RESTART_MAX
        # > 0 attaches a supervisor that owns the restart policy — watchdog
        # trips and fatal scheduler exits tear down, back off, warm-restart
        # and replay retryable requests instead of latching DOWN.
        restart_max = int(config.get_or_default("TPU_RESTART_MAX", "0"))
        if restart_max > 0 and engine.family == "llm":
            from gofr_tpu.serving.supervisor import EngineSupervisor

            EngineSupervisor(
                engine,
                max_restarts=restart_max,
                backoff_s=float(
                    config.get_or_default("TPU_RESTART_BACKOFF_S", "0.5")
                ),
                metrics=metrics,
                logger=logger,
            ).start()
        return engine

    def _init_llm_quantized(self, seed: int) -> dict:
        """Random-init the transformer leaf-by-leaf with immediate int8 or
        int4 quantization (``self.quant``) of the matmul weights (same
        fan-in-scaled normal as ``init_transformer``, different key-split
        order — irrelevant for random weights). Each leaf's bf16 tensor is
        transient inside its own jit, so an 8B tree peaks near its
        quantized footprint."""
        jax, jnp = self._jax, self._jnp
        from gofr_tpu.ops.quant import (
            _QUANT_KEYS,
            quantize_array,
            quantize_array4,
        )

        quantize_leaf = (
            quantize_array4 if self.quant == "int4" else quantize_array
        )

        cfg = self.cfg
        shapes = jax.eval_shape(
            lambda k: self.spec.init(k, cfg), jax.random.PRNGKey(0)
        )
        base = jax.random.PRNGKey(seed)
        counter = [0]

        def make(name: str, sds: Any) -> Any:
            counter[0] += 1
            key = jax.random.fold_in(base, counter[0])
            if name in ("attn_norm", "mlp_norm", "final_norm"):
                # (1+w) norm models (Gemma) use zeros as identity.
                return jnp.full(
                    sds.shape, 0.0 if cfg.norm_offset else 1.0, cfg.dtype
                )
            if name.endswith("_b"):  # QKV biases: zeros, as init_transformer
                return jnp.zeros(sds.shape, cfg.dtype)
            fan_in = sds.shape[-1] if name == "embed" else sds.shape[-2]

            def init_leaf(k: Any) -> Any:
                w = (
                    jax.random.normal(k, sds.shape, jnp.float32) * fan_in**-0.5
                ).astype(cfg.dtype)
                return quantize_leaf(w) if name in _QUANT_KEYS else w

            return jax.jit(init_leaf)(key)

        return {
            "embed": make("embed", shapes["embed"]),
            "layers": {
                k: make(k, v) for k, v in shapes["layers"].items()
            },
            "final_norm": make("final_norm", shapes["final_norm"]),
            "lm_head": make("lm_head", shapes["lm_head"]),
        }

    def _init_llm_serving_state(self) -> None:
        """(Re)build every per-boot LLM serving structure: the KV cache
        (and its paged-pool allocator), the prefix pool, the admission
        queues, and the device-resident slot-state planes.

        Called from ``__init__`` and again from :meth:`restart_sync` —
        the supervisor's warm restart. Params and compiled programs are
        deliberately NOT touched: a restart reuses the already-loaded
        pytree and the jit caches, so recovery costs cache allocation,
        not a model load + compile. Everything rebuilt here is either
        derived state (KV contents are re-prefilled by request replay)
        or bookkeeping a crashed/abandoned scheduler may have left
        inconsistent.
        """
        jax = self._jax
        mesh = self.mesh
        n_slots = self.n_slots
        from gofr_tpu.ops.kv_cache import KVCache

        if self.kv_block:
            from gofr_tpu.ops.kv_cache import PagedKVCache

            make_cache = lambda: PagedKVCache.create(  # noqa: E731
                self.cfg.n_layers, n_slots, self.max_len,
                self.cfg.n_kv_heads, self.cfg.head_dim, self.cfg.dtype,
                quant=self.kv_quant, block=self.kv_block,
                n_blocks=self.kv_pool_blocks,
            )
        else:
            make_cache = lambda: KVCache.create(  # noqa: E731
                self.cfg.n_layers, n_slots, self.max_len,
                self.cfg.n_kv_heads, self.cfg.head_dim, self.cfg.dtype,
                quant=self.kv_quant,
            )
        if mesh is not None:
            # KV heads shard over tp, the length axis over cp —
            # same layout prefill and decode.
            from gofr_tpu.models.transformer import kv_cache_specs
            from gofr_tpu.parallel.sharding import (
                named_shardings,
                prune_specs,
            )

            self.cache = jax.jit(
                make_cache,
                out_shardings=named_shardings(
                    prune_specs(
                        kv_cache_specs(
                            quantized=bool(self.kv_quant),
                            paged=bool(self.kv_block),
                            cp="cp" in mesh.axis_names,
                        ),
                        mesh,
                    ),
                    mesh,
                ),
            )()
        else:
            self.cache = make_cache()
        self._radix = None
        if self.kv_block:
            # Host-side REFCOUNTED block allocator (ops/kv_cache.py):
            # block 0 is the parking block and never handed out; the
            # table mirror uploads (8 KB) only when an admission/top-up/
            # release dirtied it. Refcounts exist for the automatic
            # prefix cache — aliased blocks are shared by many tables.
            from gofr_tpu.ops.kv_cache import BlockAllocator

            self._allocator = BlockAllocator(self.cache.n_blocks)
            self._slot_blocks: list[list[int]] = [[] for _ in range(n_slots)]
            self._table_host = np.zeros(
                (n_slots, self.max_len // self.kv_block), dtype=np.int32
            )
            self._table_dirty = False
            self._dispatched_tokens = [0] * n_slots
            if self.auto_prefix:
                # The radix index maps token content to PHYSICAL pool
                # blocks, so it is rebuilt WITH the cache planes: after
                # a supervisor warm restart the old blocks' contents are
                # gone and replayed requests re-prefill through normal
                # admission, re-warming the index as they retire.
                from gofr_tpu.serving.radix_cache import RadixPrefixIndex

                self._radix = RadixPrefixIndex(
                    self.kv_block, self._allocator,
                    max_blocks=self.prefix_cache_blocks,
                )
        # Prefix-KV reuse: shared system prompts prefill once into a
        # device pool; admission copies rows in (prefix_cache.py). A
        # restart builds a FRESH pool — the old rows died with the old
        # cache, so callers re-register (register_prefix documents this).
        self._prefix_pool = None
        if self.prefix_slots > 0:
            from gofr_tpu.serving.prefix_cache import PrefixPool

            self._prefix_pool = PrefixPool(
                self.prefix_slots, self.cache, mesh=mesh
            )
        self._slots: list[Optional[_ActiveSeq]] = [None] * n_slots
        self._prefilling: dict[int, _PrefillState] = {}
        # (first_dev, first_lp_dev, row, slot, seq) awaiting async fetch.
        self._prefill_emits: list = []
        # Paged mode: requests held back waiting for free pool blocks.
        from collections import deque as _deque

        self._wait_kv: "_deque[_GenRequest]" = _deque()
        # Tier transfers awaiting application: KVBlockPayloads a sibling
        # prefill replica shipped here (handoff_prefilled), applied by
        # the scheduler thread ahead of admission each iteration — the
        # pool blocks they fill belong to THIS boot's allocator, so the
        # deque is rebuilt (emptied) with the rest of the per-boot
        # state; a payload dropped by a restart simply re-prefills.
        self._tier_imports: "_deque[Any]" = _deque()
        # Import-completion latches (import_payload(wait_s=...)): the
        # remote-source pull waits — bounded — until the scheduler has
        # actually applied the payload, so the request submitted right
        # after deterministically admission-aliases the warm blocks
        # instead of racing its own cache warm.
        self._tier_import_done: "dict[int, Any]" = {}
        # Prefill-source export requests (export_cached): (ids, box,
        # event) triples serviced by the scheduler thread next to the
        # import apply — the radix walk and the device→host block pull
        # both touch donated planes, so no other thread may run them.
        self._tier_exports: "_deque[Any]" = _deque()
        # Watermark-sweep fruitless latch (scheduler._radix_watermark_
        # sweep): the (free, cached) signature of the last sweep that
        # found nothing evictable, so the loop skips re-scanning the
        # trie until pressure actually changes.
        self._wm_fruitless: Optional[tuple[int, int]] = None
        # SLO-class-aware admission queue (serving/lifecycle.py): the
        # queue.Queue API subset the scheduler pops through, with
        # interactive-first dequeue and a max-wait starvation bound.
        # With class_promote_s=0 (or uniform-class traffic) the pop
        # order is exactly the old FIFO.
        # Hit-aware admission ordering (TPU_QUEUE_PREFIX_AWARE, off by
        # default): the pop tie-break probes the radix index through
        # the NON-MUTATING peek — no increfs, no LRU perturbation. The
        # closure captures THIS boot's index (both rebuild together on
        # a warm restart). Off → probe None → byte-identical pop order.
        prefix_probe: Optional[Any] = None
        if self.queue_prefix_aware and self._radix is not None:
            _radix_now = self._radix
            prefix_probe = lambda req: _radix_now.peek(  # noqa: E731
                list(req.prompt_ids), getattr(req, "aid", 0)
            ) > 0
        self._pending: ClassPriorityQueue = ClassPriorityQueue(
            maxsize=self.queue_max,
            promote_after_s=self.class_promote_s,
            prefix_probe=prefix_probe,
        )
        self._work = threading.Event()
        self._tokens_dev = self._up(np.zeros((n_slots,), dtype=np.int32))
        self._logps_dev = self._up(np.zeros((n_slots,), dtype=np.float32))
        # Slot state lives ON DEVICE between windows; re-uploaded only
        # when admissions/retirements change it (dirty flag). Steady-
        # state decode then dispatches with zero host→device traffic.
        # Sampling is counter-based (seed, n_sampled) per slot — no
        # PRNG key threads through device state at all.
        self._nsteps_dev = self._up(np.zeros((n_slots,), dtype=np.int32))
        self._seeds_host = np.zeros((n_slots,), dtype=np.int32)
        self._seeds_dev = self._up(self._seeds_host)
        # Per-slot sampling-counter OFFSET at admission: 0 for fresh
        # requests; a replayed request's delivered-token count, so its
        # counter-based sample path continues where the crashed engine
        # left off (seeded-sampling replay continuity). Uploaded with
        # the seeds plane under the same dirty flag.
        self._noff_host = np.zeros((n_slots,), dtype=np.int32)
        self._noff_dev = self._up(self._noff_host)
        self._seeds_dirty = False
        # Multi-LoRA adapter plane: per-slot adapter index into the
        # stacked [L, 1+lora_slots, ...] adapter leaves (0 = base).
        # Allocated unconditionally so every compiled signature is
        # uniform; without adapter leaves in params the operand is
        # dead and XLA drops it.
        self._aids_host = np.zeros((n_slots,), dtype=np.int32)
        self._aids_dev = self._up(self._aids_host)
        self._active_dev = self._up(np.zeros((n_slots,), dtype=bool))
        self._temps_dev = self._up(np.ones((n_slots,), dtype=np.float32))
        self._topp_dev = self._up(np.ones((n_slots,), dtype=np.float32))
        self._greedy_dev = self._up(np.ones((n_slots,), dtype=bool))
        # Penalties state: per-slot generated-token counts (a [1]-wide
        # dummy when the feature is compiled out keeps one signature).
        pv = self.cfg.vocab_size if self.enable_penalties else 1
        self._pcounts_dev = self._up(
            np.zeros((n_slots, pv), dtype=np.int32)
        )
        self._fpen_dev = self._up(np.zeros((n_slots,), dtype=np.float32))
        self._ppen_dev = self._up(np.zeros((n_slots,), dtype=np.float32))
        self._bidx_host = np.full(
            (n_slots, LOGIT_BIAS_K), -1, dtype=np.int32
        )
        self._bval_host = np.zeros(
            (n_slots, LOGIT_BIAS_K), dtype=np.float32
        )
        self._bidx_dev = self._up(self._bidx_host)
        self._bval_dev = self._up(self._bval_host)
        tlk = max(1, self.top_logprobs)
        self._topi_dev = self._up(
            np.zeros((n_slots, tlk), dtype=np.int32)
        )
        self._topl_dev = self._up(
            np.zeros((n_slots, tlk), dtype=np.float32)
        )
        self._slot_state_dirty = True
        # Token history per slot (prompt + generated) — the n-gram
        # draft source; only maintained when speculation is on.
        self._history_dev = (
            self._up(np.zeros((n_slots, self.max_len), dtype=np.int32))
            if self.spec_tokens else None
        )
        # Compile-tracked paged-pool jits: the COW copy (prefix-hit
        # boundary) and the tier-transfer importer are module-level
        # fixed-shape programs; wrapping them per engine makes a mid-
        # steady-state geometry drift show up in the recompile counter
        # like any other program.
        if self.kv_block:
            from gofr_tpu.ops.kv_cache import (
                paged_copy_block,
                paged_extract_block,
                paged_insert_block,
                paged_move_block,
            )

            # shared=True: these jits' XLA caches span every engine in
            # the process — per-wrapper signature tracking keeps the
            # attribution per-engine and race-free.
            self._paged_copy_block = self._compiles.wrap(
                "paged_copy_block", paged_copy_block, shared=True
            )
            self._paged_insert_block = self._compiles.wrap(
                "paged_insert_block", paged_insert_block, shared=True
            )
            # Device-leg tier transfers (ops/kv_cache.py): fixed-shape
            # per-block extract on the exporting engine and move on the
            # importer — one compile per cache-geometry pair, tracked
            # like every other program so a steady-state transfer can
            # never hide a recompile.
            self._paged_extract_block = self._compiles.wrap(
                "paged_extract_block", paged_extract_block, shared=True
            )
            self._paged_move_block = self._compiles.wrap(
                "paged_move_block", paged_move_block, shared=True
            )
            # Placement for INBOUND device-leg block planes
            # ([L, KV, block, hd] / int8-scale [L, KV, 8, block]): on a
            # mesh the head axis shards like the pool's own planes, so
            # a device_put here reshards shard-to-shard; unsharded
            # engines share the default device and the put is a no-op.
            self._block_sharding = None
            if self.mesh is not None:
                from jax.sharding import (
                    NamedSharding,
                    PartitionSpec as _P,
                )

                self._block_sharding = NamedSharding(
                    self.mesh, _P(None, "tp", None, None)
                )
        # HBM ledger (serving/device_telemetry.py): every component this
        # boot allocated, rebuilt with the serving state so a warm
        # restart's fresh pool re-accounts exactly. The derived eviction
        # watermark is fixed per boot too — geometry and budget don't
        # move between restarts.
        self._build_hbm_ledger()
        self.effective_evict_watermark = self.prefix_evict_watermark
        if (
            self.prefix_evict_watermark <= 0
            and self.prefix_evict_hbm_frac > 0
            and self.kv_block
            and self._ledger is not None
        ):
            self.effective_evict_watermark = (
                self._ledger.derive_block_watermark(
                    self.prefix_evict_hbm_frac
                )
            )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def apply_quantization(self, mode: str) -> None:
        """Quantize weights in place (call BEFORE start / after restore).

        Weight-only int8: halves the HBM weight stream that bounds decode
        throughput; dequant fuses into the matmuls (``transformer._wein``).
        """
        mode = (mode or "").lower()
        if not mode:
            return
        if self.quant:
            # Idempotency guard (ADVICE r1): re-quantizing Q8 leaves crashes
            # inside jit with an opaque AttributeError.
            if self.quant == mode:
                return
            raise RuntimeError(
                f"params already quantized as {self.quant!r}; cannot "
                f"re-quantize as {mode!r}"
            )
        if mode not in ("int8", "int4"):
            raise ValueError(
                f"unsupported quant mode {mode!r} (int8 or int4)"
            )
        if self.family not in ("llm", "seq2seq"):
            raise ValueError(
                "quantization supports llm and seq2seq models only"
            )
        if getattr(self, "_running", False):  # __init__ calls this pre-flags
            raise RuntimeError("quantize before starting the engine")
        if self.family == "seq2seq":
            if self.mesh is not None:
                raise ValueError(
                    "quantized seq2seq does not compose with a mesh yet"
                )
            from gofr_tpu.models.t5 import quantize_t5_params

            self.params = self._jax.jit(  # graftlint: disable=GL015 — boot path (guarded: raises if the engine is running)
                lambda p: quantize_t5_params(p, mode), donate_argnums=(0,)
            )(self.params)
            self.quant = mode
            return
        from gofr_tpu.ops.quant import quantize_params

        # donate: the bf16 tree frees leaf-by-leaf as the int8 tree
        # materializes — without it peak HBM is ~1.5× the bf16 tree.
        if self.mesh is not None:
            # Sharded quantization: each Q8 leaf gets out-shardings derived
            # from its weight's PartitionSpec (the scale shards with the
            # output-channel axis), so quantized serving composes with a tp
            # mesh instead of gathering anything onto one chip.
            from gofr_tpu.models.transformer import transformer_param_specs
            from gofr_tpu.ops.quant import quantized_param_specs
            from gofr_tpu.parallel.sharding import named_shardings, prune_specs

            specs = quantized_param_specs(
                prune_specs(transformer_param_specs(self.cfg), self.mesh),
                mode,
            )
            self.params = self._jax.jit(  # graftlint: disable=GL015 — boot path (guarded: raises if the engine is running)
                partial(quantize_params, mode=mode), donate_argnums=(0,),
                out_shardings=named_shardings(specs, self.mesh),
            )(self.params)
        else:
            self.params = self._jax.jit(  # graftlint: disable=GL015 — boot path (guarded: raises if the engine is running)
                partial(quantize_params, mode=mode), donate_argnums=(0,)
            )(self.params)
        self.quant = mode

    async def start(self) -> None:
        self.start_sync()

    def start_sync(self) -> None:
        if self._running:
            return
        if self.family == "llm" and self._sched is not None:
            # A crashed scheduler may still be mid-drain; let it finish
            # before resetting flags, or its trailing `_drained = True`
            # would permanently reject submissions on the restarted engine.
            self._sched.join(timeout=10)
            self._sched = None
        # Flag resets hold the submit lock: _enqueue and the scheduler's
        # drain read these under it, and a half-visible reset (e.g.
        # _draining=False seen before _drained=False) would let a
        # submission slip into a queue the old drain already failed.
        with self._submit_lock:
            self._running = True
            self._drained = False
            self._draining = False
            self._restart_pending = False
            self._fatal = None
            self._unhealthy_reason = None
            self._queued_tokens = 0
            self._tenant_queued.clear()
            if self._tenant_ledger is not None:
                self._tenant_ledger.reset_queued()
            self._idle_evt.clear()
        self._tput.reset()
        self._set_state("SERVING")
        if self.family == "llm":
            if self._watchdog is not None:
                self._watchdog.reset()
                self._watchdog.start()
            self._sched = threading.Thread(
                target=self._scheduler_loop, name="tpu-scheduler", daemon=True
            )
            self._sched.start()
        else:
            self._batcher.start()

    async def stop(self, drain_s: float = 0.0) -> None:
        if drain_s > 0:
            await asyncio.get_running_loop().run_in_executor(
                None, partial(self.stop_sync, drain_s)
            )
        else:
            self.stop_sync()

    def stop_sync(self, drain_s: float = 0.0) -> None:
        """Stop the engine. ``drain_s > 0`` = GRACEFUL: new submissions
        get 503 while in-flight generations run to completion (up to the
        deadline) — a rolling restart should not fail live requests the
        way a hard stop's drain does."""
        if drain_s > 0 and self.family == "llm" and self._running:
            with self._submit_lock:
                self._draining = True
                self._sched_idle = False
                self._idle_evt.clear()
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                # Only the scheduler may declare the engine idle (it does
                # so under the submit lock after verifying every queue and
                # slot is empty, then sets the idle event) — polling the
                # structures from here would race requests in transit
                # between them. The event wait (vs the old 50 ms sleep
                # poll) returns the moment the scheduler publishes idle
                # or dies, so drains end as soon as the work does.
                # (_drained/_fatal also break: the scheduler's exit path
                # sets _running=False before the event today, but the
                # drain must not depend on that ordering.)
                if (
                    self._sched_idle or not self._running
                    or self._drained or self._fatal is not None
                ):
                    break
                self._idle_evt.wait(timeout=deadline - time.monotonic())
        with self._submit_lock:
            self._running = False
        if self.family == "llm":
            if self._watchdog is not None:
                self._watchdog.stop()
            self._work.set()
            if self._sched is not None:
                self._sched.join(timeout=10)
                self._sched = None
        else:
            self._batcher.stop()
        self._set_state("DOWN")

    def close(self) -> None:
        # An attached supervisor must not resurrect an engine the
        # operator is closing (and its thread must not leak).
        sup = self._supervisor
        if sup is not None:
            sup.stop()
        self.stop_sync()
        if sup is not None:
            # Final sweep: a scheduler crash racing this close may have
            # parked requests for replay after stop()'s own drain;
            # nothing will ever requeue them now (idempotent pop-and-
            # fail under the submit lock).
            sup.drain_parked()

    # ------------------------------------------------------------------
    # supervision (serving/supervisor.py)
    # ------------------------------------------------------------------

    def attach_supervisor(self, supervisor: Any) -> None:
        """Hand the restart policy to ``supervisor``: watchdog trips and
        fatal scheduler exits notify it instead of latching DOWN until
        an operator intervenes, and the scheduler's death drain parks
        retryable requests for replay instead of failing them."""
        self._supervisor = supervisor

    def set_replica_handoff(self, handoff: Optional[Any]) -> None:
        """Install a replica-pool handoff: ``handoff(req) -> bool`` is
        offered every still-retryable request this engine would
        otherwise fail terminally (crash-loop DOWN, scheduler death with
        no supervisor). True means the pool adopted it — requeued on
        another replica via :meth:`requeue_replay`, stream and future
        intact — so the client never sees this replica die."""
        self._handoff = handoff

    def try_handoff(self, req: _GenRequest) -> bool:
        """Offer one request to the attached replica-pool handoff.
        False when no handoff is installed, the request is no longer
        retryable, or the pool could not place it (the caller then runs
        its normal terminal error path). Adapter-bound requests carry
        their adapter NAME (``req.adapter``) and the pool routes them
        only to siblings advertising that adapter — the adopting
        replica re-resolves the name to its OWN slot id, so per-engine
        slot numbering never leaks across replicas. Replica-pinned
        requests are never handed off (synthetic probes must measure
        THIS replica)."""
        handoff = self._handoff
        if (
            handoff is None or req.pin_replica
            or not req.retryable()
        ):
            return False
        try:
            return bool(handoff(req))
        except Exception as exc:  # noqa: BLE001 — handoff must not mask the drain
            if self._logger is not None:
                self._logger.errorf("replica handoff failed: %s", exc)
            return False

    def set_tier_exporter(self, exporter: Optional[Any]) -> None:
        """Install the pool's tier-transfer exporter on a prefill-role
        engine: ``exporter(req, payload) -> bool`` is offered every
        just-finalized prefill (payload = the prompt's full KV blocks,
        host-bounced; None when the engine has no paged pool). True
        means the pool placed the request on a decode replica — this
        engine releases the slot and never decodes it. False (no decode
        tier, retries exhausted AND no sibling adopted it, transfer cap
        hit) means the scheduler decodes locally — the fused fallback,
        so a collapsed decode tier degrades service, never drops it."""
        self._tier_exporter = exporter

    def handoff_prefilled(self, req: _GenRequest, payload: Any) -> Optional[str]:
        """Decode-tier admission seam: adopt a request whose prompt a
        prefill replica already computed, with its KV blocks shipped as
        ``payload`` (``ops.kv_cache.KVBlockPayload``).

        The payload is NOT applied here — this runs on the pool's
        transfer path, and cache planes may only be touched by the
        scheduler thread (pipelined windows donate the live buffers).
        Instead the payload queues for the scheduler, which imports the
        blocks into the radix prefix index ahead of admission; the
        requeued request then admission-aliases them zero-copy, exactly
        like any other prefix hit. Every validation failure (geometry
        mismatch, short/corrupt payload, no paged pool or radix here)
        quietly downgrades to ``"fused"``: the request re-prefills on
        this replica — byte-identical output, just without the saved
        prefill.

        Returns ``"imported"`` (blocks queued + request admitted),
        ``"fused"`` (request admitted, blocks unusable → re-prefill
        here), or ``None`` (request not adoptable: draining, queue
        full, no longer retryable — the pool tries elsewhere)."""
        if self.family != "llm":
            return None
        # Fault seam: a decode replica rejecting the transfer (pool
        # pressure, version mismatch) — the pool retries with backoff
        # then falls back to fused serving.
        faults.fire("tier.import", engine=self, request=req)
        usable = bool(
            payload is not None
            and self.kv_block
            and self._radix is not None
            and payload.compatible_with(self.cache)
            and payload.verify()
        )
        if usable:
            self._tier_imports.append(payload)
        if not self.requeue_replay(req, mode="transfer"):
            if usable:
                try:
                    self._tier_imports.remove(payload)
                except ValueError:
                    pass  # the scheduler already consumed it: harmless cache warm
            return None
        return "imported" if usable else "fused"

    def import_payload(self, payload: Any, wait_s: float = 0.0) -> str:
        """Wire-leg import seam: adopt a KV-block payload WITHOUT a
        request — the remote decode replica's ops-port import endpoint
        (``POST /ops/tier-import``) lands here after decoding the
        length-prefixed body. Validation is exactly
        :meth:`handoff_prefilled`'s (geometry fingerprint + re-computed
        CRC over the received bytes); a usable payload queues for the
        scheduler thread, which imports it into the radix index like
        any in-proc transfer, and the separately-submitted request then
        admission-aliases the blocks zero-copy. ``"imported"`` when the
        blocks queued, ``"fused"`` when they were rejected — the
        request (which travels the ordinary OpenAI wire) re-prefills
        here either way, never a wrong answer, never a 5xx.

        ``wait_s`` > 0 waits — bounded, never past the budget — until
        the scheduler has APPLIED the payload before returning: the
        pool's remote-source pull submits its request immediately after
        the import, and without the latch the admission alias walk
        could race the apply and pay a redundant prefill (correct, just
        slower and nondeterministic for the warm-hit accounting)."""
        if self.family != "llm":
            return "fused"
        faults.fire("tier.import", engine=self, request=None)
        usable = bool(
            payload is not None
            and self.kv_block
            and self._radix is not None
            and payload.compatible_with(self.cache)
            and payload.verify()
        )
        if not usable:
            if self._logger is not None:
                self._logger.warnf(
                    "wire tier import from %s rejected (stale geometry "
                    "or corrupt payload); the request will re-prefill",
                    getattr(payload, "src", "?"),
                )
            return "fused"
        done: Optional[threading.Event] = None
        if wait_s > 0:
            done = threading.Event()
            self._tier_import_done[id(payload)] = done
        self._tier_imports.append(payload)
        # Wake the scheduler so the import applies ahead of the
        # companion request's admission when the engine is idle.
        self._work.set()
        if done is not None:
            done.wait(wait_s)
            self._tier_import_done.pop(id(payload), None)
        return "imported"

    def export_cached(
        self,
        token_ids: Any,
        *,
        timeout_s: float = 2.0,
        deadline: Optional[Any] = None,
    ) -> Optional[Any]:
        """Prefill-source export seam: hand back the longest cached
        prefix of ``token_ids`` as a shippable host payload, or None on
        a miss. This is ``import_payload`` run backwards — the ops-port
        export endpoint (``GET/POST /ops/tier-export``) lands here when
        a remote decode pod asks this prefill pod for blocks it already
        computed.

        The radix walk and block extraction run on the scheduler
        thread (donated planes); this caller-thread façade enqueues the
        request and waits on a latch BOUNDED by ``timeout_s`` (clamped
        to ``deadline`` when given — the pull must never outlive the
        request it warms). A timeout, a stopped scheduler, or any
        export failure is a miss: the asking pod prefills locally,
        never an error."""
        if self.family != "llm" or not self.kv_block or self._radix is None:
            return None
        ids = [int(t) for t in token_ids]
        if len(ids) < self.kv_block:
            return None  # shorter than one block: nothing shippable
        budget = float(timeout_s)
        if deadline is not None:
            budget = min(budget, float(deadline.remaining()))
        if budget <= 0 or not self._running:
            return None
        box: list = []
        done = threading.Event()
        self._tier_exports.append((tuple(ids), box, done))
        self._work.set()
        if not done.wait(budget):
            return None  # scheduler busy past the budget: miss, not error
        return box[0] if box else None

    def synthetic_probe(self, timeout_s: float = 30.0) -> Any:
        """Active health probe: ONE cheap greedy token through the full
        submit → prefill → decode → retire path. Raises (or times out)
        when the serving dataplane is broken in any way a real request
        would observe — the replica pool's prober demotes the replica
        and asks the supervisor to restart on that evidence, and a DOWN
        replica is re-admitted only after this passes."""
        if self.family != "llm":
            return self.health_check()
        # Pinned to THIS engine: a probe the pool fails over to a
        # healthy sibling would report a dead replica as alive.
        req = self.submit_generate(
            [1], max_new_tokens=1, temperature=0.0, stop_on_eos=False,
            pin_replica=True,
        )
        try:
            return req.future.result(timeout=timeout_s)
        finally:
            # A timed-out probe must not decode forever in a live slot.
            if not req.future.done():
                req.cancel_request()

    def _set_state(self, state: str) -> None:
        """Health state machine transition (SERVING → DEGRADED →
        RESTARTING → DOWN), mirrored to the app_tpu_engine_state gauge
        (0=SERVING 1=DEGRADED 2=RESTARTING 3=DOWN)."""
        self._state = state
        if self._metrics is not None:
            order = {"SERVING": 0, "DEGRADED": 1, "RESTARTING": 2, "DOWN": 3}
            self._metrics.set_gauge(
                "app_tpu_engine_state", order.get(state, 3),
                "model", self.model_name,
            )

    @property
    def state(self) -> str:
        return self._state

    def restart_sync(self) -> None:
        """Warm restart (the supervisor's recovery step): rebuild the
        per-boot serving state — KV cache, paged-pool allocator, queues,
        device slot planes — and start a fresh scheduler, REUSING the
        already-loaded params pytree and the compiled programs. A failed
        device dispatch may have consumed donated buffers (cache, token
        planes), so everything donated is rebuilt; params are never
        donated by the serving programs and survive as-is."""
        if self.family != "llm":
            self.stop_sync()
            self.start_sync()
            return
        if self._running:
            self.stop_sync()
        self._init_llm_serving_state()
        self.start_sync()

    def requeue_replay(self, req: _GenRequest, mode: str = "replay") -> bool:
        """Re-admit a salvaged request after a restart, bypassing the
        admission shedders (it was admitted before the crash; shedding
        the replay would fail a client the restart exists to save).
        Returns False when the request stopped being retryable during
        the restart (cancelled / deadline expired) or the fresh queue is
        already full — the caller fails it with the terminal error path.

        ``mode="transfer"`` is the disaggregated-tier admission path
        (:meth:`handoff_prefilled`): the same shedder-bypassing requeue,
        but nothing was delivered yet and nothing is being replayed, so
        the replay counter/metrics/annotations stay untouched — the
        transfer has its own (``app_tpu_tier_transfers_total``,
        ``tpu.transfer``).
        """
        if not req.retryable():
            return False
        transfer = mode == "transfer"
        # Admission-scoped fields reset so the fresh scheduler re-admits
        # from scratch — snapshotted first, because a requeue that FAILS
        # (draining engine, full queue) hands the request back to its
        # caller, whose fallback path (e.g. the tier exporter's local
        # decode) still needs the pre-requeue state intact.
        saved = (
            req.effective_prompt_len, req.replays, req.replay_skip,
            req.replayed_tokens,
        )
        req.effective_prompt_len = 0
        if not transfer:
            req.replays += 1
        if req.temperature > 0 and self.replay_exact:
            # SAMPLED stream → EXACT replay (TPU_REPLAY_EXACT, default):
            # regenerate the delivered prefix from the prompt through
            # the decode path (counter restarts at 0 and
            # deterministically re-walks the same sample path; the
            # scheduler swallows the re-generated prefix). Re-prefilling
            # the delivered tokens would write their K/V through the
            # prefill kernel, whose bf16 rounding differs from the
            # original decode writes by enough to flip a later sampled
            # token.
            req.replay_skip = len(req.token_ids)
            req.replayed_tokens = 0
        else:
            # FAST replay: re-prefill prompt + delivered tokens
            # (prefill_ids) in one pass and resume at the next position;
            # the sampling-counter offset plane restores the PRNG step
            # (ReplayState.n_sampled) so a sampled stream continues on
            # the SAME counter path. Greedy streams always take this
            # path (argmax is robust to the prefill/decode kernel
            # rounding); sampled streams take it under
            # TPU_REPLAY_EXACT=false, trading possible bf16-rounding
            # token flips for not re-decoding a long delivered prefix.
            req.replay_skip = 0
            req.replayed_tokens = len(req.token_ids)
        cost = len(req.prompt_ids) + req.max_new_tokens
        with self._submit_lock:
            if not self._running or self._drained or self._draining:
                (req.effective_prompt_len, req.replays, req.replay_skip,
                 req.replayed_tokens) = saved
                return False
            try:
                self._pending.put_nowait(req)
            except queue.Full:
                (req.effective_prompt_len, req.replays, req.replay_skip,
                 req.replayed_tokens) = saved
                return False
            self._queued_tokens += cost
            if self.tenant_queue_max and req.tenant:
                self._tenant_queued[req.tenant] = (
                    self._tenant_queued.get(req.tenant, 0) + 1
                )
            if self._tenant_ledger is not None:
                # Keep the fair-share numerator balanced (the pop will
                # note_dequeued); replays bypass the SHEDDERS, not the
                # accounting.
                self._tenant_ledger.note_enqueued(req)
            self._sched_idle = False
        self._work.set()
        if transfer:
            return True
        if req.timeline is not None:
            req.timeline.note_replay(
                "regenerate" if req.replay_skip else "re-prefill",
                self._obs.now(),
            )
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_requests_replayed_total", "model", self.model_name
            )
        if self._logger is not None:
            self._logger.infof(
                "replayed request after restart (%d token(s) already "
                "delivered, %d remaining, mode=%s)",
                len(req.token_ids), req.max_new_tokens - len(req.token_ids),
                "regenerate" if req.replay_skip else "re-prefill",
            )
        return True

    def _on_watchdog_trip(self, reason: str) -> None:
        """Watchdog callback: latch unhealthy and start a graceful
        drain — new submissions get 503 (pointing traffic at healthy
        replicas) while any work the stalled device eventually finishes
        still reaches its callers. The flags hold the submit lock like
        every other writer. With a supervisor attached the trip also
        requests a restart instead of staying latched until an operator
        intervenes."""
        with self._submit_lock:
            self._unhealthy_reason = reason
            self._draining = True
        self._set_state("DEGRADED")
        sup = self._supervisor
        if sup is not None:
            sup.notify_trip(reason)

    # ------------------------------------------------------------------
    # public LLM API
    # ------------------------------------------------------------------

    @property
    def _free_blocks(self) -> list:
        """Free-list view of the paged allocator (kept as the historical
        attribute name — tests and scripts/soak.py watch its length).
        Read-only: all mutation goes through the refcounted
        ``BlockAllocator``."""
        return self._allocator.free_blocks

    @property
    def max_prompt_tokens(self) -> int:
        """Longest admissible prompt: one generated token plus pipelined-
        window overshoot must still fit in max_len (the same invariant the
        admission-room clamp in _dispatch_prefill_chunk enforces)."""
        return self.max_len - 2 - (self.pipeline_depth + 1) * self.window_k

    def _throughput_tps(self) -> float:
        """Tokens/sec estimate for projected-wait shedding: the operator
        prior (TPU_EXPECTED_TPS) wins; otherwise the sliding-window
        AGGREGATE rate across the whole batch (lifecycle.
        AggregateThroughput — a per-request rate underestimates batched
        throughput by ~the batch size and sheds too eagerly); 50 tok/s
        as the cold-start floor so a fresh engine never divides by zero
        or sheds everything."""
        if self._expected_tps > 0:
            return self._expected_tps
        rate = self._tput.rate()
        if rate > 0:
            return rate
        return 50.0

    def _projected_wait_s(self, cost_tokens: int) -> float:
        """Seconds of queue ahead of a request costing ``cost_tokens``
        (prompt + generation budget), from the queue's token backlog
        over the throughput estimate. Reads under the submit lock."""
        return (self._queued_tokens + cost_tokens) / self._throughput_tps()

    def _note_dequeued(self, req: _GenRequest) -> None:
        """Return a popped request's tokens (and its tenant-quota seat)
        to the submit budgets."""
        cost = len(req.prompt_ids) + req.max_new_tokens
        with self._submit_lock:
            self._queued_tokens = max(0, self._queued_tokens - cost)
            if req.tenant and req.tenant in self._tenant_queued:
                left = self._tenant_queued[req.tenant] - 1
                if left > 0:
                    self._tenant_queued[req.tenant] = left
                else:  # drop empty entries: the dict stays O(live tenants)
                    del self._tenant_queued[req.tenant]
        if self._tenant_ledger is not None:
            self._tenant_ledger.note_dequeued(req)

    def shed_retry_after_s(
        self, reason: str, cost: int = 0, tenant: str = ""
    ) -> float:
        """THE Retry-After for every admission shed (ISSUE 13 bugfix:
        several 429 paths answered a near-constant projected wait that
        ignored what actually has to recover). One shared, load-
        sensitive estimate:

        * every reason starts from the queue-drain projection
          (backlog + this request over measured throughput);
        * ``hbm_headroom`` / ``brownout`` add the IN-FLIGHT decode
          backlog — headroom and burn recover as live work retires,
          not merely as the queue drains;
        * ``tenant_quota`` / ``tenant_fair_share`` are floored at the
          TENANT's own queued backlog drain (its seats free as its own
          work completes, however empty the global queue is);
        * with the brownout ladder above L0, the controller's projected
          recovery is the floor — a 429 must not invite a retry into a
          still-degraded pod.

        Always positive (the wire form ceils to an integer ≥ 1).
        Called under the submit lock; every read is host arithmetic."""
        tps = self._throughput_tps()
        # THE queue-drain projection (shared with the deadline check):
        # one formula, one place to change it.
        wait = self._projected_wait_s(max(0, cost))
        if reason in ("hbm_headroom", "brownout"):
            inflight = 0
            for seq in self._slots:
                if seq is not None:
                    inflight += max(
                        0,
                        seq.request.remaining_new_tokens
                        - seq.n_generated,
                    )
            wait += inflight / tps
        if (
            reason in ("tenant_quota", "tenant_fair_share", "tenant_brownout")
            and tenant
            and self._tenant_ledger is not None
        ):
            wait = max(
                wait,
                self._tenant_ledger.tenant_queued_tokens(tenant) / tps,
            )
        bc = self._brownout
        if bc is not None and bc.level > 0:
            wait = max(wait, bc.projected_recovery_s())
        # A tenant-brownout 429 is floored at the TENANT's own ladder
        # recovery — a retry must not land while its rungs still stand.
        cp = self._control
        if reason == "tenant_brownout" and cp is not None and tenant:
            wait = max(wait, cp.tenant_recovery_s(tenant))
        return max(wait, 0.5)

    def _shed(self, reason: str, retry_after_s: float) -> None:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_requests_shed_total",
                "model", self.model_name, "reason", reason,
            )
        if self._logger is not None:
            self._logger.warnf(
                "shedding request (%s); retry in ~%.0fs",
                reason, retry_after_s,
            )

    def _enqueue(self, req: _GenRequest) -> None:
        # Fault seam: a submit-path failure (serialization bug, OOM in
        # bookkeeping) must reject THIS request, not wedge the engine.
        faults.fire("engine.submit", engine=self, request=req)
        cost = len(req.prompt_ids) + req.max_new_tokens
        # Check-and-enqueue under the drain lock: once the scheduler's final
        # drain has run, nothing may land in the queue (it would hang) —
        # and during a GRACEFUL drain nothing may land either (503; the
        # same lock the scheduler's idle confirmation takes, so a request
        # can never slip in after the drain observed the engine idle).
        with self._submit_lock:
            if self._draining:
                from gofr_tpu.errors import ErrorServiceUnavailable

                raise ErrorServiceUnavailable(
                    "engine draining for shutdown"
                    + (
                        f" (watchdog: {self._unhealthy_reason})"
                        if self._unhealthy_reason else ""
                    )
                    + "; retry against another replica"
                )
            if self._fatal is not None:
                raise RuntimeError(f"engine scheduler died: {self._fatal}")
            if not self._running or self._drained:
                raise RuntimeError("engine not started")
            # Load shedding BEFORE admission (Orca/vLLM treat overload as
            # first-class): a bounded token budget over the submit queue
            # answers 429 + Retry-After instead of queueing unboundedly,
            # and a request whose deadline cannot survive the projected
            # queue wait is rejected NOW — burning a KV slot on a
            # generation nobody will wait for helps no one.
            from gofr_tpu.errors import (
                ErrorDeadlineExceeded,
                ErrorTooManyRequests,
            )

            wait_s = self._projected_wait_s(cost)
            # Per-tenant quota FIRST (TPU_TENANT_QUEUE_MAX): one tenant
            # flooding the queue is shed on ITS OWN budget before it can
            # exhaust the global one for everyone else.
            if (
                self.tenant_queue_max
                and req.tenant
                and self._tenant_queued.get(req.tenant, 0)
                >= self.tenant_queue_max
            ):
                retry = self.shed_retry_after_s(
                    "tenant_quota", cost, req.tenant
                )
                self._shed("tenant_quota", retry)
                raise ErrorTooManyRequests(
                    f"tenant {req.tenant!r} has "
                    f"{self._tenant_queued[req.tenant]} queued request(s) "
                    f"(TPU_TENANT_QUEUE_MAX={self.tenant_queue_max})",
                    retry_after_s=retry,
                )
            # Fairness-aware shedding (TPU_TENANT_FAIR_SHARE, ledger-
            # derived, off by default): a tenant already holding more
            # than its share of the queue budget is shed FIRST — its
            # burst degrades that tenant, not the fleet. Checked before
            # the global budgets so the hog's 429s leave room for
            # everyone else's admissions.
            if (
                self._tenant_ledger is not None
                and self.tenant_fair_share > 0
                and req.tenant
                and self._tenant_ledger.over_fair_share(
                    req.tenant, cost, self.tenant_fair_share,
                    self.queue_max_tokens, self.queue_max,
                )
            ):
                retry = self.shed_retry_after_s(
                    "tenant_fair_share", cost, req.tenant
                )
                self._shed("tenant_fair_share", retry)
                raise ErrorTooManyRequests(
                    f"tenant {req.tenant!r} is over its fair share of "
                    f"the queue budget "
                    f"(TPU_TENANT_FAIR_SHARE={self.tenant_fair_share}); "
                    f"reason=tenant_fair_share",
                    retry_after_s=retry,
                )
            # Per-tenant brownout (serving/control_plane.py): the
            # BURNING tenant's own ladder thins (L2, deterministic AIMD
            # credit) or sheds (L3) its admissions while every other
            # tenant's requests fall straight through — below L2 (and
            # with the plane off or its burn sensor degraded) this is
            # byte-identically admit-everything.
            cp = self._control
            if cp is not None and req.tenant and not cp.tenant_admit(
                req.tenant, req.slo_class
            ):
                retry = self.shed_retry_after_s(
                    "tenant_brownout", cost, req.tenant
                )
                cp.note_action(
                    "tenant_brownout", f"shed_{req.slo_class}"
                )
                self._shed("tenant_brownout", retry)
                raise ErrorTooManyRequests(
                    f"tenant {req.tenant!r} is browned out at level "
                    f"{cp.tenant_level(req.tenant)} (its SLO burn, not "
                    f"the pod's); reason=tenant_brownout",
                    retry_after_s=retry,
                )
            if self.admit_min_headroom > 0:
                # Saturation-aware admission (TPU_ADMIT_MIN_HEADROOM):
                # below the HBM headroom floor new work is shed 429 —
                # the honest answer when the paged pool is nearly full
                # is "retry elsewhere", not a mid-stream
                # kv_pool_exhausted failure after a slot was burned.
                # A non-finite ratio (a telemetry backend answering
                # NaN) must read as "no signal", never as pressure.
                headroom = self.hbm_headroom_ratio()
                if math.isfinite(headroom) and (
                    headroom < self.admit_min_headroom
                ):
                    retry = self.shed_retry_after_s("hbm_headroom", cost)
                    self._shed("hbm_headroom", retry)
                    raise ErrorTooManyRequests(
                        f"HBM headroom {headroom:.3f} below the "
                        f"admission floor {self.admit_min_headroom:.3f} "
                        f"(TPU_ADMIT_MIN_HEADROOM); retry against "
                        f"another replica",
                        retry_after_s=retry,
                    )
            # Brownout L2+ (serving/brownout.py): the effective
            # admission budget is the AIMD-cut fraction of the nominal
            # one, consumed priority-aware — batch may only fill its
            # smaller allowance (it sheds first), interactive keeps the
            # whole cut budget (it sheds last). Below L2 the fraction
            # is exactly 1.0, so this block admits byte-identically.
            bc = self._brownout
            if bc is not None and bc.shedding:
                frac = bc.admission_fraction(req.slo_class)
                if self.queue_max_tokens:
                    over = (
                        self._queued_tokens + cost
                        > frac * self.queue_max_tokens
                    )
                else:
                    over = self._pending.qsize() + 1 > frac * self.queue_max
                if over:
                    retry = self.shed_retry_after_s(
                        "brownout", cost, req.tenant
                    )
                    bc.note_action(f"shed_{req.slo_class}")
                    self._shed("brownout", retry)
                    raise ErrorTooManyRequests(
                        f"brownout level {bc.level}: admission budget "
                        f"cut to {frac:.2f} of nominal for SLO class "
                        f"{req.slo_class!r}; reason=brownout",
                        retry_after_s=retry,
                    )
            if (
                self.queue_max_tokens
                and self._queued_tokens + cost > self.queue_max_tokens
            ):
                retry = self.shed_retry_after_s("queue_tokens", cost)
                self._shed("queue_tokens", retry)
                raise ErrorTooManyRequests(
                    f"submit queue token budget exhausted "
                    f"({self._queued_tokens} queued + {cost} requested > "
                    f"{self.queue_max_tokens}; TPU_QUEUE_TOKENS)",
                    retry_after_s=retry,
                )
            if req.deadline is not None and (
                req.deadline.expired()
                or req.deadline.remaining() <= wait_s
            ):
                self._shed("deadline", wait_s)
                raise ErrorDeadlineExceeded(
                    f"projected queue wait {wait_s:.2f}s exceeds the "
                    f"request deadline "
                    f"({max(req.deadline.remaining(), 0.0):.2f}s left)"
                )
            try:
                self._pending.put_nowait(req)
            except queue.Full:
                retry = self.shed_retry_after_s("queue_full", cost)
                self._shed("queue_full", retry)
                raise ErrorTooManyRequests(
                    f"submit queue full ({self._pending.maxsize} requests; "
                    f"TPU_QUEUE_MAX)",
                    retry_after_s=retry,
                ) from None
            self._queued_tokens += cost
            if self.tenant_queue_max and req.tenant:
                self._tenant_queued[req.tenant] = (
                    self._tenant_queued.get(req.tenant, 0) + 1
                )
            if self._tenant_ledger is not None:
                self._tenant_ledger.note_enqueued(req)
            self._sched_idle = False
        self._work.set()

    def submit_generate(
        self,
        prompt: str | list[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        stop_on_eos: bool = True,
        stop: "Optional[list[str]]" = None,
        top_p: float = 1.0,
        frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0,
        seed: "Optional[int]" = None,
        logit_bias: "Optional[dict]" = None,
        top_logprobs: int = 0,
        adapter: str = "",
        deadline: "Optional[Deadline]" = None,
        deadline_s: "Optional[float]" = None,
        cancel: "Optional[CancelToken]" = None,
        tenant: str = "",
        slo_class: str = "",
        pin_replica: bool = False,
        traceparent: "Optional[str]" = None,
    ) -> _GenRequest:
        if self.family != "llm":
            raise RuntimeError(f"model {self.model_name} is not a generative LLM")
        aid = 0
        if adapter:
            from gofr_tpu.errors import ErrorInvalidParam

            if adapter not in self._lora_names:
                raise ErrorInvalidParam([
                    f"unknown LoRA adapter {adapter!r}; loaded: "
                    f"{sorted(self._lora_names)}"
                ])
            aid = self._lora_names[adapter]
        if not 0.0 < top_p <= 1.0:
            from gofr_tpu.errors import ErrorInvalidParam

            raise ErrorInvalidParam(["top_p must be in (0, 1]"])
        if top_p < 1.0 and not self.enable_top_p:
            from gofr_tpu.errors import ErrorInvalidParam

            raise ErrorInvalidParam([
                "top_p requires TPU_TOP_P=true (compiles the nucleus "
                "sort into the sampler)"
            ])
        if frequency_penalty or presence_penalty:
            from gofr_tpu.errors import ErrorInvalidParam

            if not self.enable_penalties:
                raise ErrorInvalidParam([
                    "frequency/presence penalties require TPU_PENALTIES="
                    "true (compiles the per-slot token-count plane into "
                    "the sampler)"
                ])
            if not (-2.0 <= frequency_penalty <= 2.0
                    and -2.0 <= presence_penalty <= 2.0):
                raise ErrorInvalidParam([
                    "penalties must be in [-2, 2]"
                ])
        if top_logprobs:
            from gofr_tpu.errors import ErrorInvalidParam

            if not 0 < int(top_logprobs) <= self.top_logprobs:
                raise ErrorInvalidParam([
                    f"top_logprobs must be in [1, {self.top_logprobs}] "
                    f"(the engine compiles TPU_TOP_LOGPROBS="
                    f"{self.top_logprobs} alternatives)"
                    if self.top_logprobs else
                    "top_logprobs requires TPU_TOP_LOGPROBS>0 (compiles "
                    "the per-step alternatives top_k into the sampler)"
                ])
        bias: dict = {}
        if logit_bias:
            from gofr_tpu.errors import ErrorInvalidParam

            if not isinstance(logit_bias, dict):
                raise ErrorInvalidParam([
                    "logit_bias must be an object mapping token ids to "
                    "numbers"
                ])
            # logit_bias composes with speculation since the exact-verify
            # redesign: the spec window samples through the same biased
            # `sample` closure the decode window uses, so acceptance
            # compares drafts against the biased choice itself.
            if len(logit_bias) > LOGIT_BIAS_K:
                raise ErrorInvalidParam([
                    f"logit_bias supports at most {LOGIT_BIAS_K} entries"
                ])
            try:
                if any(
                    isinstance(t, float) and t != int(t) for t in logit_bias
                ):
                    raise ValueError("fractional token id")
                bias = {
                    int(t): float(b) for t, b in logit_bias.items()
                }
            except (TypeError, ValueError):
                raise ErrorInvalidParam([
                    "logit_bias must map integral token ids to numbers"
                ]) from None
            if any(
                not 0 <= t < self.cfg.vocab_size for t in bias
            ) or any(not -100.0 <= b <= 100.0 for b in bias.values()):
                raise ErrorInvalidParam([
                    f"logit_bias token ids must be in [0, "
                    f"{self.cfg.vocab_size}) and biases in [-100, 100]"
                ])
        # Fault seam: a tokenizer failure (corrupt vocab, bad merges row)
        # must 500 this request and leave the engine serving.
        faults.fire("engine.tokenize", prompt=prompt)
        ids = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        )
        # Overlong prompts are REJECTED up front (ErrorPromptTooLong → 413)
        # unless truncation was explicitly enabled, in which case the tail
        # is kept and the result is flagged (VERDICT r1 weak #8: never
        # silently drop prompt content).
        max_prompt = self.max_prompt_tokens
        truncated = False
        if len(ids) > max_prompt:
            if not self.truncate_prompts:
                from gofr_tpu.errors import ErrorPromptTooLong

                raise ErrorPromptTooLong(len(ids), max_prompt)
            ids = ids[-max_prompt:]
            truncated = True
            if self._logger is not None:
                self._logger.warnf(
                    "prompt truncated to its last %d tokens "
                    "(TPU_TRUNCATE_PROMPTS)", max_prompt,
                )
        # Brownout SLO class: an explicit, valid X-SLO-Class wins, then
        # the tenant's configured default (TPU_TENANT_SLO_CLASS), then
        # "standard". Request-controlled, so it is clamped to the
        # bounded vocabulary before it can reach shed metrics.
        cls = self._normalize_slo_class(slo_class)
        if not cls:
            # Case-insensitive tenant match, like the per-tenant SLO
            # override keys (the map stores lower-cased keys).
            cls = self._tenant_class_map.get(
                str(tenant or "").lower(), "standard"
            )
        # L1+ generation clamp (TPU_BROWNOUT_MAX_NEW): trade answer
        # LENGTH for admission capacity before trading admissions. The
        # result advertises the deliberate truncation (`brownout` field
        # + finish_reason="length") so clients see policy, not a bug.
        brownout_clamped = False
        bc = self._brownout
        if bc is not None:
            clamped = bc.clamp_max_new(int(max_new_tokens))
            if clamped < int(max_new_tokens):
                max_new_tokens = clamped
                brownout_clamped = True
                bc.note_action("clamp_tokens")
        # Per-tenant L1+ clamp (serving/control_plane.py): the BURNING
        # tenant's generation budget is cut while everyone else's (and
        # every request below its L1) passes through untouched.
        cp = self._control
        if cp is not None and tenant:
            clamped = cp.tenant_clamp_max_new(tenant, int(max_new_tokens))
            if clamped < int(max_new_tokens):
                max_new_tokens = clamped
                brownout_clamped = True
                cp.note_action("tenant_brownout", "clamp_tokens")
        req = _GenRequest(
            prompt_ids=ids,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            stop_on_eos=stop_on_eos,
            truncated=truncated,
            stop_texts=list(stop or []),
            top_p=top_p,
            frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty,
            # Unseeded requests draw a fresh seed (distinct streams);
            # int32 range for the device plane.
            seed=(
                int(seed) & 0x7FFFFFFF if seed is not None
                else self._seed_rng.getrandbits(31)
            ),
            logit_bias=bias,
            top_logprobs=int(top_logprobs or 0),
            aid=aid,
            adapter=adapter,
            # Stamp the adapter slot's generation: if the slot is
            # reloaded/unloaded while this request is queued, admission
            # fails it instead of silently serving different weights.
            lora_gen=self._lora_gen[aid] if aid else 0,
            deadline=coalesce_deadline(deadline, deadline_s),
            tenant=str(tenant or ""),
            slo_class=cls,
            brownout_clamped=brownout_clamped,
            pin_replica=pin_replica,
        )
        if cancel is not None:
            # Share the transport's token (HTTP disconnect, gRPC cancel)
            # so tripping it retires this sequence mid-decode.
            req.cancel = cancel
        # Observability: mint the request's lifecycle timeline, adopting
        # the caller's trace context (explicit W3C traceparent from the
        # HTTP/gRPC edge, else the submitting task's current span). None
        # when the whole layer is off — the scheduler hooks all guard.
        req.timeline = self._obs.begin(
            prompt_tokens=len(ids), traceparent=traceparent,
            tenant=str(tenant or ""),
        )
        try:
            self._enqueue(req)
        except Exception as exc:
            # Shed/rejected before a slot: close the timeline with the
            # shed outcome so the flight recorder pins it and the trace
            # shows WHY admission said no — and charge the tenant's
            # shed count (the fairness signal /debug/tenants names the
            # culprit by).
            self._obs.note_shed(req.timeline, type(exc).__name__)
            if self._tenant_ledger is not None:
                self._tenant_ledger.finish_request(req, "shed")
            raise
        return req

    def register_prefix(
        self, prompt: str | list[int], adapter: str = ""
    ) -> _GenRequest:
        """Prefill a shared prompt prefix ONCE and park its KV rows in the
        device prefix pool; later prompts starting with it skip straight
        to their remainder (admission-time row copy). The request's future
        resolves with the pool row index. Requires ``prefix_slots > 0``
        (``TPU_PREFIX_SLOTS``). With ``adapter``, the prefix prefills
        under that LoRA adapter and only same-adapter requests reuse it."""
        if self.family != "llm":
            raise RuntimeError("prefix registration is for llm engines")
        aid = 0
        if adapter:
            if adapter not in self._lora_names:
                raise KeyError(f"no loaded LoRA adapter {adapter!r}")
            aid = self._lora_names[adapter]
        if self._prefix_pool is None:
            raise RuntimeError(
                "prefix pool disabled — construct the engine with "
                "prefix_slots > 0 (TPU_PREFIX_SLOTS)"
            )
        ids = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str)
            else list(prompt)
        )
        if not ids:
            raise ValueError("prefix must be at least one token")
        if len(ids) > self.max_prompt_tokens:
            from gofr_tpu.errors import ErrorPromptTooLong

            raise ErrorPromptTooLong(len(ids), self.max_prompt_tokens)
        req = _GenRequest(
            prompt_ids=ids, max_new_tokens=1, temperature=0.0,
            stop_on_eos=False, prefix_store=True, aid=aid,
            lora_gen=self._lora_gen[aid] if aid else 0,
        )
        self._enqueue(req)
        return req

    def register_prefix_sync(
        self, prompt: Any, timeout: float = 300.0, adapter: str = ""
    ) -> int:
        return self.register_prefix(prompt, adapter=adapter).future.result(
            timeout=timeout
        )

    def generate_sync(
        self, prompt: Any, timeout: float = 300.0, **kw: Any
    ) -> GenerationResult:
        return self.submit_generate(prompt, **kw).future.result(timeout=timeout)

    async def generate(self, prompt: Any, **kw: Any) -> GenerationResult:
        req = self.submit_generate(prompt, **kw)
        return await asyncio.wrap_future(req.future)

    async def generate_stream(
        self, prompt: Any, **kw: Any
    ) -> "AsyncIterator[int]":
        """Async iterator over generated token ids."""
        req = self.submit_generate(prompt, **kw)
        loop = asyncio.get_running_loop()
        while True:
            tok = await loop.run_in_executor(None, req.stream.get)
            if tok is None:
                return
            yield tok


    def mesh_topology(self) -> Optional[dict]:
        """The serving mesh's shape (axes, device count, device names)
        or ``None`` when unsharded — advertised through health probes,
        pool replica descriptors, and ``/debug/flight`` so an operator
        can see each replica's pod layout (dp across replicas, tp
        within) without shelling into it."""
        from gofr_tpu.parallel.mesh import mesh_topology

        return mesh_topology(self.mesh)

    # ------------------------------------------------------------------
    # device-resource observability (serving/device_telemetry.py)
    # ------------------------------------------------------------------

    def _device_memory_stats(self) -> Optional[dict]:
        """One mesh device's (or the default device's) runtime memory
        accounting, None on backends without it (CPU)."""
        try:
            if self.mesh is not None:
                dev = next(iter(self.mesh.devices.flat))
            else:
                dev = self._jax.local_devices()[0]
            stats = dev.memory_stats()
            return dict(stats) if stats else None
        except Exception:  # graftlint: disable=GL006 — gauge-only path; memory_stats support varies by backend
            return None

    def _build_hbm_ledger(self) -> None:
        """Account every device-resident component this boot allocated
        into an :class:`HBMLedger`. Sizes are attribute reads on
        already-built arrays — no device traffic — and fixed per boot,
        so this runs once per (re)start."""
        from gofr_tpu.serving.device_telemetry import (
            HBMLedger,
            tree_device_bytes,
        )

        layers = (
            self.params.get("layers", {})
            if isinstance(self.params, dict) else {}
        )
        lora_bytes = sum(
            tree_device_bytes(v) for k, v in layers.items()
            if k.endswith("_lora_a") or k.endswith("_lora_b")
        )
        components: dict[str, int] = {
            "params": tree_device_bytes(self.params) - lora_bytes,
        }
        if lora_bytes:
            components["lora"] = lora_bytes
        block_bytes = n_blocks = 0
        if self.family == "llm":
            cache = self.cache
            # Exactly the pool's own hbm_bytes() — the ledger must
            # agree with the allocator's accounting to the byte
            # (tests pin this at tp=1 AND tp=2).
            components["kv_pool"] = cache.hbm_bytes()
            workspace = tree_device_bytes([
                cache.lengths, getattr(cache, "block_table", None),
                self._tokens_dev, self._logps_dev, self._nsteps_dev,
                self._seeds_dev, self._noff_dev, self._aids_dev,
                self._active_dev, self._temps_dev, self._topp_dev,
                self._greedy_dev, self._pcounts_dev, self._fpen_dev,
                self._ppen_dev, self._bidx_dev, self._bval_dev,
                self._topi_dev, self._topl_dev, self._history_dev,
            ])
            components["workspace"] = workspace
            if self._prefix_pool is not None:
                components["prefix_pool"] = self._prefix_pool.hbm_bytes()
            if self.kv_block:
                block_bytes = cache.block_bytes()
                n_blocks = cache.n_blocks
        self._ledger = HBMLedger(
            components,
            mesh_devices=(
                int(self.mesh.devices.size) if self.mesh is not None else 1
            ),
            block_bytes=block_bytes,
            n_blocks=n_blocks,
            budget_bytes=self.hbm_budget_bytes,
            device_stats=self._device_memory_stats,
        )
        self._ledger.publish(self._metrics, self.model_name)

    def hbm_ledger(self) -> dict:
        """The HBM ledger's snapshot (components, totals, budget,
        headroom, platform cross-check) — ``/debug/capacity``'s hbm
        block and the health detail."""
        if self._ledger is None:
            return {}
        return dict(self._ledger.snapshot(self._ledger_free_blocks()))

    def _ledger_free_blocks(self) -> int:
        if self.family == "llm" and self.kv_block:
            return int(self._allocator.n_free)
        return 0

    def _kv_pool_counts(self) -> tuple[int, int, int]:
        """Paged-pool pressure counts ``(total, used, cached)`` —
        allocatable blocks (block 0 parks), blocks held by live tables
        or the radix index, and the radix-cached (reclaimable) subset.
        The ONE accounting both the scheduler's gauge pass and
        ``capacity_report`` read, so Prometheus and /debug/capacity can
        never disagree."""
        total = self.cache.n_blocks - 1
        used = max(0, total - self._allocator.n_free)
        cached = (
            self._radix.n_cached_blocks if self._radix is not None else 0
        )
        return total, used, cached

    def hbm_headroom_ratio(self) -> float:
        """THE saturation signal: fraction of the per-device HBM budget
        currently free (budget slack + free paged-KV blocks). Read by
        admission shedding (TPU_ADMIT_MIN_HEADROOM), the radix eviction
        watermark (TPU_PREFIX_EVICT_HBM_FRAC), and the pool scaler
        (TPU_SCALE_UP_HEADROOM). O(1) host arithmetic."""
        if self._ledger is None:
            return 1.0
        return float(
            self._ledger.headroom_ratio(self._ledger_free_blocks())
        )

    def mark_steady_state(self) -> None:
        """Arm the compile tracker's warm-up fence: every XLA compile
        after this call counts (and warns) as a steady-state recompile
        — always a fixed-shape-discipline bug. Bench calls this after
        its warm-up phase; operators after a canary sweep."""
        self._compiles.mark_warm()

    def compile_stats(self) -> dict:
        """The compile tracker's snapshot: per-program compile counts
        and wall clock, the steady-state recompile count, and whether
        the warm-up fence is armed."""
        return dict(self._compiles.snapshot())

    def tenant_report(self) -> dict:
        """The tenant ledger's full unclamped table (``/debug/tenants``
        on the ops port): per-tenant tokens, KV-block·seconds, outcome
        counts, live queue share, and the conservation anchor.
        ``{"enabled": False}`` when the layer is off
        (``TPU_TENANT_LEDGER=0``)."""
        if self._tenant_ledger is None:
            return {"enabled": False}
        report = dict(self._tenant_ledger.snapshot())
        report["fair_share"] = self.tenant_fair_share
        return report

    def slo_report(self) -> dict:
        """The SLO engine's burn-rate state (``/debug/slo`` on the ops
        port). ``{"enabled": False}`` when no objective is configured."""
        if self._slo is None:
            return {"enabled": False}
        return dict(self._slo.snapshot())

    def brownout_report(self) -> dict:
        """The brownout controller's full state (``/debug/brownout`` on
        the ops port): ladder level, AIMD budget factor, thresholds,
        last control inputs, per-action counters. ``{"enabled": False}``
        with the layer off (``TPU_BROWNOUT=0`` or no SLOs configured —
        the burn rate is the control signal)."""
        if self._brownout is None:
            return {"enabled": False}
        return dict(self._brownout.snapshot())

    def control_report(self) -> dict:
        """The control plane's full state (``/debug/control`` on the
        ops port): per-signal guard state, per-loop mode + hold-down
        timers, the decision ring. ``{"enabled": False}`` when the
        layer is off (``TPU_CONTROL_PLANE=0`` or a non-LLM family)."""
        if self._control is None:
            return {"enabled": False}
        return dict(self._control.snapshot())

    def control_scale_pressure(self) -> Optional[int]:
        """The control plane's scale-up advertisement (1 = the
        host-overhead or predictive loop asserts pressure), ``None``
        when the plane is off — the pool scaler's None-vs-0 distinction
        (signal absent vs armed-and-calm), mirroring
        :meth:`brownout_level`."""
        if self._control is None:
            return None
        return int(self._control.scale_pressure())

    def attach_async_lag(
        self,
        read: "Callable[[], float]",
        *,
        depth: Optional[float] = None,
        sustain_s: Optional[float] = None,
    ) -> bool:
        """Register the async serving plane's consumer-lag sensor with
        the control plane (``serving/async_serving.py`` calls this at
        plane construction): sustained backlog then feeds PoolScaler
        pressure through :meth:`control_scale_pressure` like any other
        scaling loop. ``depth``/``sustain_s`` > 0 re-point the lag
        loop's thresholds; False = control plane off (signal skipped —
        off is off)."""
        cp = self._control
        if cp is None:
            return False
        if (depth is not None and depth > 0) or (
            sustain_s is not None and sustain_s > 0
        ):
            cp.async_loop.configure(
                depth if depth and depth > 0 else cp.async_loop.depth,
                sustain_s if sustain_s and sustain_s > 0
                else cp.async_loop.sustain_s,
            )
        cp.register("async_lag", read)
        return True

    def brownout_level(self) -> Optional[int]:
        """The current degradation level, ``None`` when the layer is
        off (``TPU_BROWNOUT=0`` / no SLOs) — the distinction matters to
        the pool, where None means "signal absent" (never suppress
        hedges/probes or count scaler pressure) while 0 means "armed
        and nominal"."""
        return None if self._brownout is None else self._brownout.level

    def slo_compliant(self) -> Optional[bool]:
        """THE routing signal (ReplicaPool.pick deprioritizes on it,
        closing the ROADMAP item): the SLO engine's compliance bit AND
        the brownout ladder below L3. None when no SLOs are
        configured. Reads the CACHED bit — pick() calls this per
        candidate per request, and a full ring scan there would contend
        with the retirement path under exactly the overload this signal
        exists for; every observation and health/probe pass refreshes
        the cache."""
        if self._brownout is not None and not self._brownout.routable():
            return False
        if self._slo is None:
            return None
        return bool(self._slo.compliant_cached())

    def _loop_context(self) -> dict[str, Any]:
        """The serving state a loop-anomaly record freezes at the stall
        instant (queue depth, occupancy, brownout level, HBM headroom —
        what an operator needs to tell "overloaded" from "wedged").
        Called on the scheduler thread only, host values already in
        hand — no device pulls."""
        in_use = sum(1 for s in self._slots if s is not None)
        ctx: dict[str, Any] = {
            "queue_depth": int(self._pending.qsize()),
            "wait_kv": len(self._wait_kv),
            "prefilling": len(self._prefilling),
            "occupancy": round(in_use / max(1, self.n_slots), 6),
            "hbm_headroom_ratio": round(self.hbm_headroom_ratio(), 6),
            "brownout_level": self.brownout_level(),
        }
        if self.kv_block:
            ctx["kv_blocks_free"] = int(self._allocator.n_free)
        if self._control is not None:
            # Which sensors were degraded at the stall instant — a
            # stall that coincides with a lying sensor is a different
            # investigation than one under healthy signals.
            ctx["control_degraded"] = sorted(
                name
                for name, health in self._control.signal_health().items()
                if health < 1.0
            )
        return ctx

    def loop_report(self) -> dict:
        """The scheduler-loop profiler's full state (``/debug/loop`` on
        the ops port): per-phase rolling stats, utilization /
        host-overhead ratio, stall thresholds, anomaly rings, and the
        profiler's own measured overhead. ``{"enabled": False}`` when
        the layer is off (``TPU_LOOP_PROFILE=0``)."""
        if self._loop_prof is None:
            return {"enabled": False}
        return dict(self._loop_prof.snapshot())

    def capacity_report(self) -> dict:
        """``/debug/capacity``'s per-engine record: the HBM ledger,
        compile counts, paged-pool pressure, and the heaviest tenants
        in one read."""
        report: dict[str, Any] = {
            "model": self.model_name,
            "state": self._state,
            "hbm": self.hbm_ledger(),
            "compiles": self.compile_stats(),
        }
        if self._loop_prof is not None:
            # "Where do the passes go" next to "how full is the
            # device" — the loop-time signal beside the byte signal.
            report["loop"] = self._loop_prof.describe()
        if self._tenant_ledger is not None:
            # "Which tenant filled it" next to "how full is it".
            report["tenants"] = self._tenant_ledger.top_tenants()
        if self._slo is not None:
            report["slo"] = self._slo.describe()
        if self._brownout is not None:
            # "Is this pod browning out" next to "is it breaking its
            # promise" — the actuator's state beside its signal.
            report["brownout"] = self._brownout.describe()
        if self._control is not None:
            # The control plane's headline: scale pressure, degraded
            # sensors, and how many tenants are on their own ladder.
            report["control"] = self._control.describe()
        if self.family == "llm" and self.kv_block:
            total, used, cached = self._kv_pool_counts()
            pool: dict[str, Any] = {
                "block_tokens": self.kv_block,
                "total_blocks": total,
                "free_blocks": total - used,
                "used_blocks": used,
                "occupancy_ratio": round(used / max(1, total), 6),
                "evict_watermark": self.effective_evict_watermark,
                "evict_watermark_source": (
                    "explicit" if self.prefix_evict_watermark > 0
                    else (
                        "hbm_frac" if self.effective_evict_watermark > 0
                        else "off"
                    )
                ),
            }
            if self._radix is not None:
                pool["cached_blocks"] = cached
                pool["fragmentation_ratio"] = round(
                    cached / used, 6
                ) if used else 0.0
            report["kv_pool"] = pool
        return report

    def flight_records(self) -> dict:
        """The flight recorder's current contents (``/debug/flight`` on
        the ops port): the ring of recent request timelines plus the
        pinned slow/errored ones. ``{"enabled": False}`` when the
        recorder is off (TPU_FLIGHT_RECORDER=0)."""
        recorder = self._obs.recorder
        if recorder is None:
            return {"enabled": False}
        out = {
            "enabled": True,
            # The device-resource headline rides every flight read: an
            # operator chasing tail latency sees HBM pressure and
            # steady-state recompiles next to the slow timelines.
            "hbm_headroom_ratio": round(self.hbm_headroom_ratio(), 6),
            "steady_state_recompiles": (
                self._compiles.steady_state_recompiles
            ),
            **recorder.snapshot(),
        }
        if self._tenant_ledger is not None:
            # The attribution headline: slow-timeline readers see WHO
            # holds the pool without a second request.
            out["tenants"] = self._tenant_ledger.top_tenants()
        if self._loop_prof is not None:
            # The loop headline (the headroom idiom): slow timelines
            # next to "was the scheduler itself stalling".
            out["loop"] = self._loop_prof.describe()
        return out

    def health_check(self) -> dict:
        devices = self._jax.devices()
        details: dict[str, Any] = {
            "model": self.model_name,
            "family": self.family,
            "devices": [str(d) for d in devices],
            "running": self._running,
            # Supervision state machine (serving/supervisor.py):
            # SERVING → DEGRADED (trip/crash detected) → RESTARTING
            # (supervisor recovering) → DOWN (stopped or restart budget
            # exhausted). Inside details so it rides the typed gRPC
            # HealthReply's details_json too.
            "state": self._state,
        }
        mesh_topo = self.mesh_topology()
        if mesh_topo is not None:
            # Pod shape: a pool probing this replica (in-proc or over
            # HTTP) lifts the mesh from the health payload into its
            # descriptors — dp across replicas, tp within each.
            details["mesh"] = mesh_topo
        sup = self._supervisor
        if sup is not None:
            details["supervisor"] = sup.describe()
        unhealthy = self._unhealthy_reason
        if self._watchdog is not None or unhealthy is not None:
            details["watchdog"] = {
                "tripped": unhealthy is not None,
                "reason": unhealthy or "",
                "bound_s": (
                    self._watchdog.bound_s
                    if self._watchdog is not None else 0.0
                ),
            }
        if self.family == "llm":
            details["kv_slots"] = {
                "total": self.n_slots,
                "in_use": sum(1 for s in self._slots if s is not None),
            }
            details["max_len"] = self.max_len
            details["pending"] = self._pending.qsize()
            details["prefilling"] = len(self._prefilling)
            # Disaggregated-tier role (TPU_REPLICA_ROLES): which serving
            # phase this engine owns in its pool ("fused" = both).
            details["tier_role"] = self.tier_role
            # Advertised capability set: a replica pool fronting this
            # engine over HTTP reads the loaded adapters from the health
            # payload to route LoRA requests only where their weights
            # actually live (service/replica_pool.py).
            details["lora_adapters"] = self.lora_names()
            if self.kv_block:
                details["kv_blocks"] = {
                    "block": self.kv_block,
                    "total": self.cache.n_blocks - 1,  # block 0 parks
                    "free": len(self._free_blocks),
                }
                if self._radix is not None:
                    details["prefix_cache"] = {
                        "cached_blocks": self._radix.n_cached_blocks,
                        "lookups": self._prefix_lookups,
                        "hit_tokens": self._prefix_hit_tokens,
                    }
                    # Prefill-source capability (export_cached): a pool
                    # probing this replica over HTTP reads this to
                    # discover that finished KV blocks can be PULLED
                    # from here through /ops/tier-export — the
                    # multi-host disaggregation seam. "dma" says the
                    # process can stage transfer-server handles (the
                    # cheap control-plane reply) as well as inline wire
                    # bodies.
                    details["tier_source"] = {
                        "export": True,
                        "dma": True,
                    }
        if self._ledger is not None:
            # Device-resource observability: the ledger's compact form
            # (components + headroom) rides health so pool probes —
            # in-proc and over HTTP — lift the saturation signal into
            # their replica descriptors without another endpoint.
            snap = self.hbm_ledger()
            details["hbm_ledger"] = {
                "components": snap.get("components", {}),
                "total_bytes": snap.get("total_bytes", 0),
                "per_device_bytes": snap.get("per_device_bytes", 0),
                "budget_bytes": snap.get("budget_bytes", 0),
                "budget_source": snap.get("budget_source", ""),
                "headroom_ratio": snap.get("headroom_ratio", 1.0),
            }
            details["compiles"] = {
                "total": self._compiles.total,
                "steady_state_recompiles": (
                    self._compiles.steady_state_recompiles
                ),
            }
            if self._compiles.cache_info is not None:
                # Persistent compile-cache provenance
                # (TPU_COMPILE_CACHE_DIR): warm restarts re-load
                # executables from here instead of re-tracing.
                details["compiles"]["compile_cache"] = dict(
                    self._compiles.cache_info
                )
        if self._slo is not None:
            # SLO advertisement: pool probes (in-proc and over HTTP)
            # lift compliance + fast-window burn into their replica
            # descriptors, the same path the HBM headroom rides.
            details["slo"] = self._slo.describe()
        if self._brownout is not None:
            # Brownout advertisement rides the same probe path: remote
            # pools lift the level to suppress hedges/probes against a
            # browning-out replica and to deprioritize it at L3.
            details["brownout"] = self._brownout.describe()
        if self._control is not None:
            # Control-plane advertisement (the same probe path): remote
            # pools lift `scale_pressure` into their descriptors so the
            # scaler sees the host-overhead/predictive loops' verdict
            # without another endpoint.
            details["control"] = self._control.describe()
        if self._loop_prof is not None:
            # Scheduler-loop advertisement (the headroom idiom): probes
            # and health readers see utilization / host-overhead /
            # stall counts without the full /debug/loop read.
            details["loop"] = self._loop_prof.describe()
        if self._tenant_ledger is not None:
            details["tenant_ledger"] = {
                "tenants": len(self._tenant_ledger.snapshot()["tenants"]),
                "fair_share": self.tenant_fair_share,
            }
        try:
            stats = devices[0].memory_stats()
            if stats:
                details["hbm"] = {
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
        except Exception as exc:  # noqa: BLE001
            # Not all backends report memory; surface why rather than
            # dropping the gauge silently.
            if self._logger is not None:
                self._logger.debugf("memory_stats unavailable: %s", exc)
        status = (
            "UP"
            if self._running and unhealthy is None
            and self._state == "SERVING"
            else "DOWN"
        )
        return {"status": status, "state": self._state, "details": details}
