"""Byte-level BPE tokenizer: native C++ core with a pure-Python fallback.

The serving engine's only CPU-bound ingress work is prompt encoding; the
C++ core (``native/bpe_tokenizer.cpp``, C ABI via ctypes — this image has
no pybind11) runs it off the GIL. The pure-Python :class:`PyBPE` implements
the identical greedy lowest-rank-first merge and doubles as the test
oracle; :func:`load_bpe` prefers the native core and silently falls back
when no compiler is available.

Vocab/merges file formats are hex-per-line (see :func:`write_bpe_files`),
chosen so the C++ side needs no JSON/unicode handling.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

from typing import Optional, Sequence

from gofr_tpu.analysis import lockcheck

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "bpe_tokenizer.cpp")
_SO = os.path.join(_NATIVE_DIR, "build", "libbpe.so")
_build_lock = lockcheck.make_lock("native_tokenizer._build_lock")


def build_native(force: bool = False) -> Optional[str]:
    """Compile the C++ core once (g++ -O2 -shared); None if unavailable."""
    with _build_lock:
        if not force and os.path.exists(_SO):
            return _SO
        if not os.path.exists(_SRC):
            return None
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        try:
            # Single-flight by design: the build lock held across the
            # compile is what makes "compile the C++ core once" true
            # when N workers race the first encode; losers wait and
            # then hit the os.path.exists fast path. Bounded by the
            # subprocess timeout; never on a request path after boot.
            subprocess.run(  # graftlint: disable=GL022 — single-flight native build; bounded by timeout=120
                ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            return None
        return _SO


def write_bpe_files(
    vocab: Sequence[bytes], merges: Sequence[tuple[bytes, bytes]], directory: str
) -> tuple[str, str]:
    """Write hex-per-line vocab/merges files both cores load."""
    os.makedirs(directory, exist_ok=True)
    vocab_path = os.path.join(directory, "vocab.hex")
    merges_path = os.path.join(directory, "merges.hex")
    with open(vocab_path, "w") as fp:
        for tok in vocab:
            fp.write(tok.hex() + "\n")
    with open(merges_path, "w") as fp:
        for a, b in merges:
            fp.write(f"{a.hex()} {b.hex()}\n")
    return vocab_path, merges_path


def byte_vocab_with_merges(
    merges: Sequence[tuple[bytes, bytes]], specials: int = 3
) -> list[bytes]:
    """Standard byte-level vocab: 256 single bytes, then one token per merge
    (its concatenation), then ``specials`` reserved ids (BOS/EOS/PAD)."""
    vocab = [bytes([i]) for i in range(256)]
    vocab += [a + b for a, b in merges]
    vocab += [f"<special{i}>".encode() for i in range(specials)]
    return vocab


class PyBPE:
    """Pure-Python reference implementation (and no-compiler fallback)."""

    def __init__(self, vocab_path: str, merges_path: str) -> None:
        self.id_to_token: list[bytes] = []
        self.vocab: dict[bytes, int] = {}
        with open(vocab_path) as fp:
            for i, line in enumerate(fp):
                tok = bytes.fromhex(line.strip())
                self.id_to_token.append(tok)
                self.vocab[tok] = i
        self.merge_rank: dict[tuple[bytes, bytes], int] = {}
        if os.path.exists(merges_path):
            with open(merges_path) as fp:
                for rank, line in enumerate(fp):
                    a, _, b = line.strip().partition(" ")
                    self.merge_rank[(bytes.fromhex(a), bytes.fromhex(b))] = rank

    @property
    def vocab_size(self) -> int:
        return len(self.id_to_token)

    def encode_bytes(self, data: bytes) -> list[int]:
        symbols = [bytes([b]) for b in data]
        while len(symbols) > 1:
            best_rank, best_i = None, -1
            for i in range(len(symbols) - 1):
                rank = self.merge_rank.get((symbols[i], symbols[i + 1]))
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank, best_i = rank, i
            if best_rank is None:
                break
            symbols[best_i : best_i + 2] = [symbols[best_i] + symbols[best_i + 1]]
        ids: list[int] = []
        for s in symbols:
            if s in self.vocab:
                ids.append(self.vocab[s])
            else:
                ids.extend(self.vocab.get(bytes([c]), 0) for c in s)
        return ids

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        return b"".join(
            self.id_to_token[i] for i in ids if 0 <= i < len(self.id_to_token)
        )


class NativeBPE:
    """ctypes binding over the C++ core; API-identical to :class:`PyBPE`."""

    def __init__(self, vocab_path: str, merges_path: str, so_path: str) -> None:
        lib = ctypes.CDLL(so_path)
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_vocab_size.restype = ctypes.c_int32
        lib.bpe_vocab_size.argtypes = [ctypes.c_void_p]
        lib.bpe_encode.restype = ctypes.c_int32
        lib.bpe_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
        lib.bpe_decode.restype = ctypes.c_int32
        lib.bpe_decode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32,
        ]
        self._lib = lib
        self._h = lib.bpe_create(vocab_path.encode(), merges_path.encode())
        if not self._h:
            raise OSError(f"bpe_create failed for {vocab_path}")

    @property
    def vocab_size(self) -> int:
        return int(self._lib.bpe_vocab_size(self._h))

    def encode_bytes(self, data: bytes) -> list[int]:
        cap = max(len(data), 1)
        buf = (ctypes.c_int32 * cap)()
        n = self._lib.bpe_encode(self._h, data, len(data), buf, cap)
        if n < -1:  # buffer too small (cannot happen: merges only shrink)
            cap = -n
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.bpe_encode(self._h, data, len(data), buf, cap)
        if n < 0:
            raise OSError("bpe_encode failed")
        return list(buf[:n])

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        arr = (ctypes.c_int32 * len(ids))(*ids)
        cap = 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.bpe_decode(self._h, arr, len(ids), buf, cap)
            if n >= 0:
                return buf.raw[:n]
            if n == -1:
                raise OSError("bpe_decode failed")
            cap = -n

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.bpe_free(h)
            self._h = None


class BPETokenizer:
    """Serving-engine :class:`~gofr_tpu.serving.tokenizer.Tokenizer` over
    either core. Special ids default to the last three vocab slots
    (the layout :func:`byte_vocab_with_merges` produces)."""

    def __init__(
        self,
        core,
        bos_id: Optional[int] = None,
        eos_id: Optional[int] = None,
        pad_id: Optional[int] = None,
    ) -> None:
        self._core = core
        size = core.vocab_size
        self.bos_id = bos_id if bos_id is not None else size - 3
        self.eos_id = eos_id if eos_id is not None else size - 2
        self.pad_id = pad_id if pad_id is not None else size - 1
        self.vocab_size = size

    @property
    def is_native(self) -> bool:
        return isinstance(self._core, NativeBPE)

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + self._core.encode_bytes(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        specials = {self.bos_id, self.eos_id, self.pad_id}
        return self._core.decode_bytes(
            [i for i in ids if i not in specials]
        ).decode("utf-8", "replace")


def load_bpe(
    vocab_path: str, merges_path: str, prefer_native: bool = True, **kw
) -> BPETokenizer:
    """Load a BPE tokenizer, native core first, pure Python otherwise."""
    if prefer_native:
        so = build_native()
        if so is not None:
            try:
                return BPETokenizer(NativeBPE(vocab_path, merges_path, so), **kw)
            except OSError:
                pass
    return BPETokenizer(PyBPE(vocab_path, merges_path), **kw)
