"""Device-resource observability: the HBM ledger and the XLA compile
tracker (ISSUE 11).

The serving stack is bounded by two device resources that, until this
module, were invisible: **HBM bytes** (params, adapter slots, the paged
KV pool, workspace planes) and **XLA compilations** (a steady-state
recompile silently serializes the whole dispatch pipeline behind a
multi-second trace+compile). Both failure modes today surface only as
mysterious tail latency in the phase histograms. This module gives each
a first-class accounting layer the control paths (admission shedding,
radix eviction watermark, pool scaling) can act on:

* :class:`HBMLedger` — a per-engine byte ledger over the components the
  engine actually allocated: ``params`` (quantized weight tree minus
  adapter leaves), ``lora`` (the stacked adapter planes), ``kv_pool``
  (the slot or paged cache, exactly ``cache.hbm_bytes()``), optional
  ``prefix_pool``, and ``workspace`` (block table, lengths, and the
  per-slot device state planes). All byte counts are **global logical
  bytes** — identical at ``tp=1`` and ``tp=2`` (a sharded array's
  ``size × itemsize`` is its global footprint) — with a
  ``per_device_bytes`` estimate that divides the mesh-sharded
  components by the mesh size. The ledger resolves an HBM **budget**
  (operator ``TPU_HBM_BYTES`` > platform ``device.memory_stats()``
  ``bytes_limit`` > the ledger's own per-device total) and derives the
  **headroom ratio** — budget slack plus free paged-KV blocks over the
  budget — the one saturation signal admission, eviction, and scaling
  all read. Exported as ``app_tpu_hbm_bytes{component}`` gauges plus
  ``app_tpu_hbm_headroom_ratio``.

* :class:`CompileTracker` — wraps every jitted serving program (the
  ``serving/programs.py`` builders, the paged-KV importer/COW jits, the
  modality steps) and counts actual XLA cache growth per call
  (``fn._cache_size()`` deltas; a shape-signature set is the fallback
  on backends without the introspection). Every compile increments
  ``app_tpu_compiles_total{program}``, records the call's wall clock in
  ``app_tpu_compile_seconds`` (first-call trace+compile time — the
  latency a request actually pays), and emits a deferred ``tpu.compile``
  span via the PR 6 ``Tracer.emit_span`` idiom (parented under the
  trace that was ambient at engine construction, so a traced boot owns
  its warm-up compiles even though they fire on the scheduler thread).
  After :meth:`CompileTracker.mark_warm` — the warm-up fence — any
  further compile bumps ``app_tpu_steady_state_recompiles_total`` and
  logs a warning: a recompile in steady state is **always** a
  fixed-shape-discipline bug (graftlint GL015 is the static twin).

Overhead contract: the wrapper adds two cache-size reads and two clock
reads per *dispatch* (window/chunk granularity, never per token); the
ledger's component bytes are computed once per boot (sizes are static)
and the headroom ratio is O(1) arithmetic over the allocator's free
count.

Determinism: clocks are injectable and nothing here sleeps or touches
device state — tests drive compiles with real programs and read exact
counts.
"""

from __future__ import annotations

import math

import time
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck
from gofr_tpu.serving.observability import tracer_active
from gofr_tpu.tracing import get_tracer
from gofr_tpu.tracing.tracer import _rand_hex, current_span


def tree_device_bytes(tree: Any) -> int:
    """Total bytes of every array leaf in a (possibly nested) pytree-ish
    structure — duck-typed on ``.size``/``.dtype`` so it never imports
    jax and costs attribute reads only (no host↔device traffic)."""
    total = 0
    stack = [tree]
    while stack:
        node = stack.pop()
        if node is None:
            continue
        size = getattr(node, "size", None)
        dtype = getattr(node, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * int(getattr(dtype, "itemsize", 1))
        elif isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
    return total


class HBMLedger:
    """Byte accounting of one engine's device-resident components plus
    the derived headroom signal. Component sizes are fixed per boot
    (buffers are preallocated); the only dynamic input is the paged
    pool's free-block count, passed into :meth:`headroom_ratio` by the
    caller so the ledger itself holds no engine reference."""

    #: Components sharded across the mesh (params Megatron-style, the
    #: KV pool's head axis, adapter leaves, the prefix pool); workspace
    #: planes are replicated.
    SHARDED = ("params", "lora", "kv_pool", "prefix_pool")

    def __init__(
        self,
        components: dict[str, int],
        *,
        mesh_devices: int = 1,
        block_bytes: int = 0,
        n_blocks: int = 0,
        budget_bytes: int = 0,
        budget_source: str = "",
        device_stats: Optional[Callable[[], Optional[dict]]] = None,
    ) -> None:
        self.components = {k: int(v) for k, v in components.items()}
        self.mesh_devices = max(1, int(mesh_devices))
        #: Global bytes of ONE paged pool block across every layer's
        #: K/V (and scale) planes — the unit the eviction watermark
        #: converts HBM fractions into.
        self.block_bytes = int(block_bytes)
        self.n_blocks = int(n_blocks)
        self._device_stats = device_stats
        self.budget_bytes, self.budget_source = self._resolve_budget(
            int(budget_bytes)
        )

    # -- totals --------------------------------------------------------

    @property
    def total_bytes(self) -> int:
        return sum(self.components.values())

    @property
    def per_device_bytes(self) -> int:
        """Estimated bytes resident on ONE mesh device: sharded
        components divide by the mesh size, workspace planes are
        replicated. Exact at ``tp=1``; an estimate under GSPMD (XLA may
        replicate small leaves)."""
        if self.mesh_devices <= 1:
            return self.total_bytes
        total = 0
        for name, size in self.components.items():
            if name in self.SHARDED:
                total += -(-size // self.mesh_devices)
            else:
                total += size
        return total

    def _resolve_budget(self, explicit: int) -> tuple[int, str]:
        """The per-device HBM budget headroom is measured against:
        the operator's explicit bytes, else the platform's
        ``memory_stats()['bytes_limit']``, else the ledger's own
        per-device total (headroom then reads as "free paged blocks
        over own footprint" — still a usable pressure signal on
        backends that report nothing)."""
        if explicit > 0:
            return explicit, "env"
        stats = self.device_memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return int(limit), "memory_stats"
        return self.per_device_bytes, "ledger"

    def device_memory_stats(self) -> Optional[dict]:
        """The platform's own per-device accounting when it provides
        one (TPU runtimes do; the CPU backend returns None) — the
        cross-check against the ledger's estimate."""
        if self._device_stats is None:
            return None
        try:
            stats = self._device_stats()
        except Exception:  # noqa: BLE001  # graftlint: disable=GL006 — gauge-only cross-check; memory_stats support varies by backend
            return None
        return dict(stats) if stats else None

    # -- the saturation signal -----------------------------------------

    def headroom_ratio(self, free_blocks: int = 0) -> float:
        """Fraction of the per-device budget currently free: budget
        slack beyond the ledger's allocations plus the bytes of free
        paged-KV blocks (preallocated but holding no live tokens).
        In [0, 1]; with no paged pool and an unknown budget this reads
        0.0 — honest: nothing is known to be free."""
        budget = self.budget_bytes
        if budget <= 0:
            return 1.0
        slack = max(0, budget - self.per_device_bytes)
        free = slack + (
            free_blocks * self.block_bytes // self.mesh_devices
        )
        return max(0.0, min(1.0, free / budget))

    def derive_block_watermark(self, hbm_frac: float) -> int:
        """``TPU_PREFIX_EVICT_HBM_FRAC`` → a free-block watermark: the
        number of paged pool blocks that must stay free so total free
        HBM (budget slack + free blocks) covers ``hbm_frac`` of the
        budget. Clamped to the pool size minus the parking block; 0
        when the fraction is unset or the pool has no blocks."""
        if hbm_frac <= 0 or self.block_bytes <= 0 or self.n_blocks <= 1:
            return 0
        budget = self.budget_bytes
        slack = max(0, budget - self.per_device_bytes)
        want = hbm_frac * budget - slack
        per_device_block = max(1, self.block_bytes // self.mesh_devices)
        blocks = math.ceil(want / per_device_block)
        return max(0, min(blocks, self.n_blocks - 1))

    # -- rendering -----------------------------------------------------

    def snapshot(self, free_blocks: int = 0) -> dict[str, Any]:
        """The ``/debug/capacity`` / health-detail form: components,
        totals, budget provenance, headroom, and the platform
        cross-check when one exists."""
        out: dict[str, Any] = {
            "components": dict(self.components),
            "total_bytes": self.total_bytes,
            "per_device_bytes": self.per_device_bytes,
            "mesh_devices": self.mesh_devices,
            "budget_bytes": self.budget_bytes,
            "budget_source": self.budget_source,
            "headroom_ratio": round(self.headroom_ratio(free_blocks), 6),
        }
        stats = self.device_memory_stats()
        if stats is not None:
            # Platform cross-check: what the runtime itself thinks is
            # resident vs the ledger's per-device estimate (the delta
            # is XLA workspace + fragmentation the ledger can't see).
            out["device"] = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            }
        if self.block_bytes:
            out["block_bytes"] = self.block_bytes
        return out

    def publish(self, metrics: Any, model_name: str) -> None:
        """Export the per-component gauges (once per boot — sizes are
        static; the headroom gauge refreshes per window from the
        scheduler's gauge pass)."""
        if metrics is None:
            return
        for component, size in self.components.items():
            metrics.set_gauge(
                "app_tpu_hbm_bytes", float(size),
                "model", model_name, "component", component,
            )
        metrics.set_gauge(
            "app_tpu_hbm_headroom_ratio", self.headroom_ratio(),
            "model", model_name,
        )


class CompileTracker:
    """Counts XLA compiles per jitted serving program and polices the
    steady-state fixed-shape contract. See the module docstring."""

    def __init__(
        self,
        model_name: str,
        *,
        metrics: Any = None,
        logger: Any = None,
        clock: Callable[[], float] = time.perf_counter,
        wall_ns: Callable[[], int] = time.time_ns,
    ) -> None:
        self.model_name = model_name
        self._metrics = metrics
        self._logger = logger
        self._clock = clock
        self._wall_ns = wall_ns
        self._lock = lockcheck.make_lock("CompileTracker._lock")
        self._programs: dict[str, dict[str, Any]] = {}
        self.total = 0
        self.steady_state_recompiles = 0
        self._warm = False
        # Persistent-compile-cache provenance (TPU_COMPILE_CACHE_DIR):
        # set by the engine at boot when the operator points jax's
        # compilation cache at a directory; rides health details and
        # /debug/capacity so "did this restart re-trace" is answerable.
        self.cache_info: Optional[dict[str, Any]] = None
        # Boot trace context: compiles fire on the scheduler thread
        # (no ambient span there), so the trace that was ambient when
        # the ENGINE was constructed parents the warm-up compile spans
        # — a traced boot owns its compile timeline.
        span = current_span()
        self._boot_ctx: Optional[tuple[str, str]] = (
            (span.trace_id, span.span_id) if span is not None else None
        )

    # -- warm-up fence -------------------------------------------------

    def mark_warm(self) -> None:
        """Arm the steady-state fence: every compile after this call is
        a fixed-shape-discipline bug and counts (and warns) as such.
        Callers (bench after its warm-up phase, operators after a
        canary request sweep) decide when the program set is complete."""
        self._warm = True

    @property
    def warm(self) -> bool:
        return self._warm

    # -- instrumentation -----------------------------------------------

    def wrap(self, program: str, fn: Any, shared: bool = False) -> Any:
        """Wrap a jitted callable: each call that grows the program's
        XLA cache counts as one compile of ``program``. Transparent to
        callers (same signature, same return).

        ``shared=True`` is for module-level jits whose XLA cache is
        shared by every engine in the process (the paged-pool COW and
        import programs): ``_cache_size()`` on those is GLOBAL, so a
        concurrent compile by a sibling engine would be mis-attributed
        to whichever wrapper happened to be mid-call — including a
        false steady-state recompile. Shared wraps use the per-wrapper
        shape-signature set instead: exact per-engine attribution (one
        count per variant per boot), no cross-engine race."""
        with self._lock:
            self._programs.setdefault(
                program, {"compiles": 0, "seconds_total": 0.0}
            )
        signatures: set = set()
        sig_lock = lockcheck.make_lock("CompileTracker.sig_lock")

        def cache_size() -> Optional[int]:
            if shared:
                return None
            probe = getattr(fn, "_cache_size", None)
            if probe is None:
                return None
            try:
                return int(probe())
            except Exception:  # noqa: BLE001  # graftlint: disable=GL006 — best-effort introspection; the signature fallback takes over
                return None

        def wrapped(*args: Any, **kwargs: Any) -> Any:
            before = cache_size()
            w0 = self._wall_ns()
            t0 = self._clock()
            out = fn(*args, **kwargs)
            after = cache_size()
            if before is not None and after is not None:
                compiled = after > before
            else:
                # Shared jits, fake backends, exotic jax versions: a
                # shape/dtype signature never seen by THIS wrapper is
                # the first trace of that program variant here.
                sig = _call_signature(args, kwargs)
                with sig_lock:
                    compiled = sig not in signatures
                    signatures.add(sig)
            if compiled:
                self._note_compile(program, self._clock() - t0, w0)
            return out

        return wrapped

    def _note_compile(
        self, program: str, duration_s: float, start_wall_ns: int
    ) -> None:
        steady = False
        with self._lock:
            entry = self._programs.setdefault(
                program, {"compiles": 0, "seconds_total": 0.0}
            )
            entry["compiles"] += 1
            entry["seconds_total"] += duration_s
            self.total += 1
            if self._warm:
                steady = True
                self.steady_state_recompiles += 1
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_compiles_total",
                "model", self.model_name, "program", program,
            )
            self._metrics.record_histogram(
                "app_tpu_compile_seconds", duration_s,
                "model", self.model_name,
            )
            if steady:
                self._metrics.increment_counter(
                    "app_tpu_steady_state_recompiles_total",
                    "model", self.model_name, "program", program,
                )
        if steady and self._logger is not None:
            self._logger.warnf(
                "STEADY-STATE RECOMPILE of %s (%.2fs): a compile after "
                "the warm-up fence is a fixed-shape-discipline bug — "
                "some operand's shape/dtype or a static arg changed "
                "(graftlint GL015 is the static twin of this counter)",
                program, duration_s,
            )
        self._emit_span(program, duration_s, start_wall_ns, steady)

    def _emit_span(
        self,
        program: str,
        duration_s: float,
        start_wall_ns: int,
        steady: bool,
    ) -> None:
        """Deferred ``tpu.compile`` span (PR 6 ``emit_span`` idiom:
        already-completed, explicit wall timestamps, never touches the
        ambient contextvar). Joins the calling thread's ambient trace
        when one exists, else the boot trace captured at construction,
        else mints its own."""
        tracer = get_tracer()
        if not tracer_active(tracer):
            return
        span = current_span()
        if span is not None:
            trace_id: str = span.trace_id
            parent_id: Optional[str] = span.span_id
        elif self._boot_ctx is not None:
            trace_id, parent_id = self._boot_ctx
        else:
            trace_id, parent_id = _rand_hex(16), None
        tracer.emit_span(
            "tpu.compile",
            trace_id=trace_id,
            parent_span_id=parent_id,
            start_ns=start_wall_ns,
            end_ns=start_wall_ns + int(duration_s * 1e9),
            attributes={
                "tpu.model": self.model_name,
                "tpu.program": program,
                "tpu.steady_state": steady,
            },
            status="ERROR" if steady else "OK",
        )

    # -- rendering -----------------------------------------------------

    def set_cache_info(self, info: dict[str, Any]) -> None:
        """Record the persistent compile cache's provenance (dir,
        enabled, error) — shown by :meth:`snapshot` with a live entry
        count where the directory is readable."""
        self.cache_info = dict(info)

    def _cache_snapshot(self) -> Optional[dict[str, Any]]:
        if self.cache_info is None:
            return None
        out = dict(self.cache_info)
        try:
            import os

            out["entries"] = len(os.listdir(str(out.get("dir", ""))))
        except OSError:
            # Not created yet (jax writes lazily on first compile) or
            # unreadable — provenance still reports.
            pass
        return out

    def snapshot(self) -> dict[str, Any]:
        cache = self._cache_snapshot()
        with self._lock:
            out: dict[str, Any] = {
                "total": self.total,
                "steady_state_recompiles": self.steady_state_recompiles,
                "warm": self._warm,
                "programs": {
                    name: {
                        "compiles": entry["compiles"],
                        "seconds_total": round(entry["seconds_total"], 6),
                    }
                    for name, entry in sorted(self._programs.items())
                },
            }
        if cache is not None:
            out["compile_cache"] = cache
        return out


def _call_signature(args: tuple, kwargs: dict) -> tuple:
    """Shape/dtype signature of a call's operands (the shared-jit /
    fallback compile detector): array-likes key by (shape, dtype),
    dict/tuple pytrees recurse, scalars by value — mirroring what
    distinguishes XLA cache entries under fixed-shape discipline.
    Attribute reads only: nothing here may repr() an array (that
    materializes it on host) or the detector itself would become a
    hot-path sync."""

    def sig(x: Any) -> Any:
        shape = getattr(x, "shape", None)
        if shape is not None:
            return (tuple(shape), str(getattr(x, "dtype", "")))
        if isinstance(x, dict):
            return tuple(sorted((k, sig(v)) for k, v in x.items()))
        if isinstance(x, (list, tuple)):
            return tuple(sig(i) for i in x)
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        return type(x).__name__

    return (
        tuple(sig(a) for a in args),
        tuple(sorted((k, sig(v)) for k, v in kwargs.items())),
    )
