"""Non-LLM model families behind the engine: encoder embeddings,
vision classification, and seq2seq (batched one-shot + stepped
streaming), plus the family-dispatching ``infer`` seam. Mixin
methods on InferenceEngine — split from ``engine.py`` (r4 VERDICT
weak #10)."""

from __future__ import annotations

import asyncio
import os
import time
from typing import Any

import numpy as np

from gofr_tpu.serving.batcher import pad_bucket
from gofr_tpu.serving.types import _PREFILL_BUCKETS


class ModalityMixin:
    """Encoder / vision / seq2seq execution + generic dispatch."""

    def _build_encoder_step(self) -> None:
        from gofr_tpu.models.bert import bert_embed

        cfg = self.cfg
        # Compile-tracked like every other serving program.
        self._embed_step = self._compiles.wrap(
            "embed",
            self._jax.jit(
                lambda params, tokens, mask: bert_embed(
                    params, tokens, mask, cfg
                )
            ),
        )

    def _build_seq2seq_step(self) -> None:
        from gofr_tpu.models.t5 import (
            t5_encode,
            t5_generate,
            t5_generate_chunk,
        )

        cfg = self.cfg
        max_new = self._seq2seq_max_new = int(
            os.environ.get("TPU_SEQ2SEQ_MAX_NEW", "64")
        )
        eos = self.spec.eos_token
        self._seq2seq_step = self._compiles.wrap(
            "seq2seq",
            self._jax.jit(
                lambda params, tokens, lengths: t5_generate(
                    params, tokens, lengths, cfg, max_new=max_new, eos_id=eos
                )
            ),
        )
        # Stepped decode for STREAMING (r4 VERDICT weak #7): encode once,
        # then advance the answer buffer TPU_SEQ2SEQ_CHUNK greedy steps
        # per dispatch with a host fetch (and client emit) per chunk. The
        # buffer is padded to a chunk multiple so every dispatch has one
        # static shape; greedy picks match the one-shot program exactly.
        chunk = self._seq2seq_chunk = max(
            1, int(os.environ.get("TPU_SEQ2SEQ_CHUNK", "8"))
        )
        self._seq2seq_buf_len = ((max_new + chunk - 1) // chunk) * chunk
        self._seq2seq_encode = self._compiles.wrap(
            "seq2seq_encode",
            self._jax.jit(
                lambda params, tokens, lengths: t5_encode(
                    params, tokens, lengths, cfg
                )
            ),
        )
        self._seq2seq_chunk_step = self._compiles.wrap(
            "seq2seq_chunk",
            self._jax.jit(
                lambda params, buf, done, enc, lengths, start: t5_generate_chunk(
                    params, buf, done, enc, lengths, start, cfg, chunk, eos
                ),
                donate_argnums=(1, 2),
            ),
        )

    def _build_vision_step(self) -> None:
        cfg = self.cfg
        fwd = self.spec.forward
        if fwd is None:
            raise ValueError(
                f"vision model {self.model_name} registered without a "
                f"forward fn (ModelSpec.forward)"
            )
        self._classify_step = self._compiles.wrap(
            "classify",
            self._jax.jit(
                lambda params, images: fwd(params, images, cfg)
            ),
        )


    # ------------------------------------------------------------------
    # encoder / vision APIs (dynamic batching)
    # ------------------------------------------------------------------

    def _execute_embed(self, texts: list) -> list:
        jnp = self._jnp
        encoded = [
            self.tokenizer.encode(t)[: self.max_len] if isinstance(t, str) else list(t)
            for t in texts
        ]
        bucket = pad_bucket(max(len(e) for e in encoded), _PREFILL_BUCKETS)
        bucket = min(bucket, self.max_len)
        tokens = np.zeros((len(encoded), bucket), dtype=np.int32)
        mask = np.zeros((len(encoded), bucket), dtype=np.int32)
        for i, ids in enumerate(encoded):
            ids = ids[:bucket]
            tokens[i, : len(ids)] = ids
            mask[i, : len(ids)] = 1
        t0 = time.time()
        out = np.asarray(
            self._embed_step(self.params, jnp.asarray(tokens), jnp.asarray(mask))
        )
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "embed"
            )
        return [out[i] for i in range(len(encoded))]

    def _execute_classify(self, images: list) -> list:
        jnp = self._jnp
        batch = np.stack([np.asarray(img, dtype=np.float32) for img in images])
        t0 = time.time()
        logits = np.asarray(self._classify_step(self.params, jnp.asarray(batch)))
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "classify"
            )
        return [logits[i] for i in range(len(images))]

    def _execute_seq2seq(self, texts: list) -> list:
        jnp = self._jnp
        encoded = [
            self.tokenizer.encode(t)[: self.max_len]
            if isinstance(t, str) else list(t)
            for t in texts
        ]
        bucket = pad_bucket(max(len(e) for e in encoded), _PREFILL_BUCKETS)
        bucket = min(bucket, self.max_len)
        tokens = np.zeros((len(encoded), bucket), dtype=np.int32)
        lengths = np.zeros((len(encoded),), dtype=np.int32)
        for i, ids in enumerate(encoded):
            ids = ids[:bucket]
            tokens[i, : len(ids)] = ids
            lengths[i] = len(ids)
        t0 = time.time()
        out = np.asarray(self._seq2seq_step(
            self.params, jnp.asarray(tokens), jnp.asarray(lengths)
        ))
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "seq2seq"
            )
        eos = self.spec.eos_token
        results = []
        for i in range(len(encoded)):
            ids = out[i].tolist()
            # Trim at EOS only: pad zeros exist solely AFTER an emitted
            # EOS (t5_generate), and id 0 is a legitimate vocab token a
            # model may emit mid-sequence.
            if eos in ids:
                ids = ids[: ids.index(eos)]
            results.append(ids)
        return results

    def seq2seq_stream_blocking(self, text):
        """Stepped seq2seq decode: yields lists of fresh token ids, one
        list per chunk dispatch (EOS-trimmed; stops at EOS or max_new).
        Token-identical to ``seq2seq_sync`` — both run the same decoder
        math over the same fixed buffer."""
        if self.family != "seq2seq":
            raise RuntimeError(
                f"model {self.model_name} is not a seq2seq model"
            )
        jnp = self._jnp
        ids = (
            self.tokenizer.encode(text)
            if isinstance(text, str) else list(text)
        )[: self.max_len]
        bucket = min(
            pad_bucket(max(len(ids), 1), _PREFILL_BUCKETS), self.max_len
        )
        ids = ids[:bucket]
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, : len(ids)] = ids
        lengths = jnp.asarray([len(ids)], jnp.int32)
        t0 = time.time()
        enc = self._seq2seq_encode(self.params, jnp.asarray(tokens), lengths)
        buf = jnp.zeros((1, 1 + self._seq2seq_buf_len), jnp.int32)
        done = jnp.zeros((1,), bool)
        eos = self.spec.eos_token
        chunk = self._seq2seq_chunk
        emitted = 0
        for start in range(0, self._seq2seq_buf_len, chunk):
            buf, done = self._seq2seq_chunk_step(
                self.params, buf, done, enc, lengths,
                jnp.asarray(start, jnp.int32),
            )
            # Designed sync point: each chunk's tokens must reach the host
            # to detect EOS before deciding whether to dispatch the next
            # chunk — the seq2seq loop is host-driven by construction.
            toks = np.asarray(  # graftlint: disable=GL001
                buf[0, start + 1 : start + 1 + chunk]
            ).tolist()
            fresh, hit_eos = [], False
            for t in toks:
                if t == eos:
                    hit_eos = True
                    break
                fresh.append(int(t))
            fresh = fresh[: self._seq2seq_max_new - emitted]
            emitted += len(fresh)
            if fresh:
                yield fresh
            if hit_eos or emitted >= self._seq2seq_max_new:
                break
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0,
                "kind", "seq2seq_stream",
            )

    async def seq2seq_stream(self, text):
        """Async bridge over ``seq2seq_stream_blocking`` (device waits
        run in the default executor so the event loop stays live)."""
        loop = asyncio.get_running_loop()
        gen = self.seq2seq_stream_blocking(text)
        while True:
            toks = await loop.run_in_executor(None, next, gen, None)
            if toks is None:
                return
            yield toks

    def seq2seq_sync(self, text, timeout: float = 120.0) -> list:
        """Text-to-text generation (T5 family): returns generated token
        ids (EOS-trimmed, unpadded)."""
        return self._batcher.submit(text).result(timeout=timeout)

    async def seq2seq(self, text) -> list:
        return await asyncio.wrap_future(self._batcher.submit(text))

    async def seq2seq_text(self, text) -> tuple:
        """(decoded_text, token_ids) — the ONE dispatch-and-decode used
        by ctx.infer and both gRPC surfaces, so reply shaping can't
        drift between them."""
        ids = await self.seq2seq(text)
        decoded = (
            self.tokenizer.decode(ids) if self.tokenizer is not None else ""
        )
        return decoded, ids

    def embed_sync(self, text, timeout: float = 60.0) -> np.ndarray:
        return self._batcher.submit(text).result(timeout=timeout)

    async def embed(self, text) -> np.ndarray:
        return await asyncio.wrap_future(self._batcher.submit(text))

    def classify_sync(self, image, timeout: float = 60.0) -> np.ndarray:
        return self._batcher.submit(image).result(timeout=timeout)

    async def classify(self, image) -> np.ndarray:
        return await asyncio.wrap_future(self._batcher.submit(image))

    # ------------------------------------------------------------------
    # generic dispatch + health (container contract)
    # ------------------------------------------------------------------

    async def infer(self, inputs: Any, model: str = "", **kw) -> Any:
        """`ctx.infer` seam: dispatch on family."""
        if self.family == "llm":
            result = await self.generate(inputs, **kw)
            return {
                "text": result.text,
                "tokens": len(result.token_ids),
                "ttft_ms": round(result.ttft_s * 1e3, 2),
            }
        if self.family == "encoder":
            emb = await self.embed(inputs)
            return {"embedding": emb.tolist()}
        if self.family == "seq2seq":
            text, ids = await self.seq2seq_text(inputs)
            return {"text": text, "token_ids": ids}
        vec = await self.classify(inputs)
        return {"logits": vec.tolist(), "class": int(np.argmax(vec))}

    def infer_sync(self, inputs: Any, model: str = "", **kw) -> Any:
        if self.family == "llm":
            result = self.generate_sync(inputs, **kw)
            return {
                "text": result.text,
                "tokens": len(result.token_ids),
                "ttft_ms": round(result.ttft_s * 1e3, 2),
            }
        if self.family == "encoder":
            return {"embedding": self.embed_sync(inputs).tolist()}
        if self.family == "seq2seq":
            ids = self.seq2seq_sync(inputs)
            text = (
                self.tokenizer.decode(ids)
                if self.tokenizer is not None else ""
            )
            return {"text": text, "token_ids": ids}
        vec = self.classify_sync(inputs)
        return {"logits": vec.tolist(), "class": int(np.argmax(vec))}

