"""The device-trace capture singleton (ISSUE 15, satellite of the
scheduler-loop profiler).

``jax.profiler`` is a process-wide resource: exactly one trace may run
at a time, and every capture serializes its protobuf output to disk on
``stop_trace``. Two call sites share it — the operator's manual
``/debug/tpu-trace`` endpoint (``gofr_tpu/app.py``) and the
scheduler-loop profiler's anomaly auto-trigger
(``serving/loop_profiler.py``) — so the machinery lives here as ONE
process-wide :class:`ProfilerCapture`:

* **One trace dir, one lock, created at construction.** The previous
  endpoint minted ``self._trace_dir``/``self._trace_lock`` lazily via
  ``hasattr`` on the first request, so two concurrent first requests
  could each observe the attribute missing, mint two dirs/locks, and
  trace concurrently. :func:`get_capture` constructs the singleton once
  under a module lock; the dir is reused by every capture (each
  overwrites the last — an unauthenticated loop of trace requests must
  not fill the disk).
* **Cooldown for auto-triggers** (``TPU_LOOP_TRACE_COOLDOWN_S``): a
  stall *storm* would otherwise re-trigger a capture per anomaly and
  thrash the profiler — serializing trace output is itself host work
  that widens the stall. :meth:`trigger` suppresses anything inside the
  cooldown (counted, so ``/debug/loop`` shows what was skipped); the
  manual endpoint is never cooldown-gated (an operator asking is an
  operator asking) but does note its capture so the next auto-trigger
  backs off from it.
* **Non-blocking for the scheduler.** ``trigger`` hands the bounded
  capture to a daemon thread and returns immediately — the scheduler
  loop must never block for the capture window it is trying to
  diagnose.

Determinism: clock, sleep, the start/stop callables, and the thread
spawn are all injectable, so the cooldown and concurrency contracts are
tested with stated time and synchronous spawns.
"""

from __future__ import annotations

import tempfile
import threading

import time
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck


class ProfilerCapture:
    """One process-wide ``jax.profiler`` capture slot: a reusable trace
    directory, a non-blocking busy lock, and an auto-trigger cooldown.
    Construct via :func:`get_capture` — a second instance would defeat
    the whole point."""

    def __init__(
        self,
        *,
        cooldown_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        starter: Optional[Callable[[str], None]] = None,
        stopper: Optional[Callable[[], None]] = None,
        spawn: Optional[Callable[[Callable[[], None]], None]] = None,
        logger: Any = None,
    ) -> None:
        #: One reusable directory per process; every capture overwrites
        #: the last, so repeated captures cannot fill the disk.
        self.trace_dir = tempfile.mkdtemp(prefix="tpu-trace-")
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._clock = clock
        self._sleep = sleep
        self._starter = starter
        self._stopper = stopper
        self._spawn = spawn
        self._logger = logger
        # The capture slot: held for the duration of one trace. A
        # threading (not asyncio) lock — the auto-trigger fires from
        # the scheduler thread; the async endpoint polls it
        # non-blocking and replies 409 instead of queueing.
        self._busy = lockcheck.make_lock("ProfilerCapture._busy")
        # Bookkeeping (counters + cooldown anchor) under its own lock
        # so trigger() stays race-free against note_manual_capture().
        self._state_lock = lockcheck.make_lock("ProfilerCapture._state_lock")
        self.captures = 0
        self.suppressed = 0
        self.last_capture_at: Optional[float] = None
        self.last_reason = ""
        self.last_error = ""

    # -- the capture slot ----------------------------------------------

    def try_acquire(self) -> bool:
        """Claim the capture slot without blocking (False = a capture
        is already running — the endpoint's 409)."""
        return self._busy.acquire(blocking=False)

    def release(self) -> None:
        self._busy.release()

    @property
    def busy(self) -> bool:
        return self._busy.locked()

    # -- profiler plumbing ---------------------------------------------

    def start_trace(self) -> None:
        """Start a device trace into the singleton dir (blocking disk /
        runtime work — callers keep it off their event loop)."""
        if self._starter is not None:
            self._starter(self.trace_dir)
            return
        import jax

        jax.profiler.start_trace(self.trace_dir)

    def stop_trace(self) -> None:
        if self._stopper is not None:
            self._stopper()
            return
        import jax

        jax.profiler.stop_trace()

    def note_manual_capture(self) -> None:
        """Record an endpoint-driven capture (counts, cooldown anchor):
        the next auto-trigger backs off from a trace the operator just
        took rather than stacking a second one onto the same incident."""
        with self._state_lock:
            self.captures += 1
            self.last_capture_at = self._clock()
            self.last_reason = "manual"

    # -- anomaly auto-trigger ------------------------------------------

    def trigger(self, ms: int, reason: str = "loop-stall") -> bool:
        """Fire-and-forget bounded capture for a loop anomaly: claims
        the slot and spawns the capture off-thread, or returns False
        when inside the cooldown / already busy (both counted as
        suppressed — a stall storm must not thrash the profiler).
        Never blocks the calling (scheduler) thread."""
        ms = max(1, int(ms))
        with self._state_lock:
            now = self._clock()
            if (
                self.last_capture_at is not None
                and now - self.last_capture_at < self.cooldown_s
            ):
                self.suppressed += 1
                return False
            if not self.try_acquire():
                self.suppressed += 1
                return False
            self.captures += 1
            self.last_capture_at = now
            self.last_reason = reason

        def run() -> None:
            try:
                self.start_trace()
                self._sleep(ms / 1e3)
                self.stop_trace()
                with self._state_lock:
                    self.last_error = ""
            except Exception as exc:  # noqa: BLE001 — a failed capture must never take the scheduler with it
                with self._state_lock:
                    self.last_error = f"{type(exc).__name__}: {exc}"
                if self._logger is not None:
                    self._logger.warnf(
                        "loop-anomaly trace capture failed: %s", exc
                    )
            finally:
                self.release()

        if self._spawn is not None:
            self._spawn(run)
        else:
            threading.Thread(
                target=run, name="tpu-trace-capture", daemon=True
            ).start()
        return True

    # -- rendering -----------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        with self._state_lock:
            return {
                "trace_dir": self.trace_dir,
                "busy": self.busy,
                "cooldown_s": self.cooldown_s,
                "captures": self.captures,
                "suppressed": self.suppressed,
                "last_reason": self.last_reason,
                "last_error": self.last_error,
            }


_capture: Optional[ProfilerCapture] = None
_capture_lock = lockcheck.make_lock("profiler_capture._capture_lock")


def get_capture(cooldown_s: Optional[float] = None) -> ProfilerCapture:
    """The process-wide singleton, constructed exactly once under a
    module lock (closing the lazy-``hasattr`` race the old endpoint
    had: two concurrent first requests can no longer mint two
    dirs/locks and trace concurrently). ``cooldown_s`` updates the
    auto-trigger cooldown when given — the engine passes its
    ``TPU_LOOP_TRACE_COOLDOWN_S`` through here at boot."""
    global _capture
    with _capture_lock:
        if _capture is None:
            _capture = ProfilerCapture()
        if cooldown_s is not None:
            _capture.cooldown_s = max(0.0, float(cooldown_s))
        return _capture
