"""Dynamic batching (net-new; SURVEY §2.6 maps it onto the reference's
middleware-chain idiom).

Coalesces concurrent requests into padded batch executions: a request queue
drained by a worker that flushes on **size** (max_batch reached) or
**deadline** (max_wait elapsed since the oldest pending request), padding to
power-of-two buckets so XLA reuses a small set of compiled shapes.

Thread-based (device calls block anyway): async callers get a
``concurrent.futures.Future`` they can await via ``asyncio.wrap_future``.

Overload: the queue is bounded and a full queue **sheds** — submit
raises :class:`gofr_tpu.errors.ErrorTooManyRequests`, which the HTTP
responder maps to 429 + ``Retry-After`` (the LLM engine's submit path
applies the same policy; docs/advanced-guide/resilience.md).

This module is in the strict-mypy scope (pyproject ``[tool.mypy]``).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence


def pad_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ n (last bucket caps)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class _Pending:
    payload: Any
    future: Future = field(default_factory=Future)
    enqueued_at: float = field(default_factory=time.time)


class DynamicBatcher:
    """Generic size/deadline batcher.

    ``execute(payloads) -> results`` runs on the worker thread; one result
    per payload, order-preserving. Exceptions fail the whole flush's futures.
    """

    def __init__(
        self,
        execute: Callable[[list], list],
        max_batch: int = 8,
        max_wait_s: float = 0.005,
        metrics: Any = None,
        name: str = "batcher",
        max_queue: int = 1024,
    ) -> None:
        self._execute = execute
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self._metrics = metrics
        self._name = name
        self._queue: queue.Queue[_Pending] = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # EWMA of flush (execute) wall time — the load-sensitive basis
        # for the queue-full Retry-After (ISSUE 13: a constant 1s told
        # clients to hammer an overloaded batcher at 1 Hz regardless of
        # how deep the backlog actually was).
        self._flush_ewma_s = 0.0

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"batcher-{self._name}", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def submit(self, payload: Any) -> Future:
        """Enqueue; a full queue SHEDS with 429 + Retry-After (a bounded
        queue that 500s on overload trains clients to retry immediately,
        which is the opposite of what an overloaded batcher needs)."""
        pending = _Pending(payload)
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            from gofr_tpu.errors import ErrorTooManyRequests

            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_tpu_requests_shed_total",
                    "model", self._name, "reason", "queue_full",
                )
            raise ErrorTooManyRequests(
                f"{self._name} batch queue full "
                f"({self._queue.maxsize} pending)",
                retry_after_s=self._retry_after_s(),
            ) from None
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_queue_depth", self._queue.qsize(), "batcher", self._name
            )
        return pending.future

    def _retry_after_s(self) -> float:
        """Load-sensitive Retry-After for a queue-full shed: the
        backlog in flush units times the measured flush time (the wait
        window floors it while the EWMA is cold). Always ≥ 1s (the wire
        form ceils)."""
        flushes = -(-self._queue.qsize() // max(1, self.max_batch))
        per_flush = max(self._flush_ewma_s, self.max_wait_s)
        return max(1.0, flushes * per_flush)

    # -- worker -----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._collect()
            if not batch:
                continue
            if self._metrics is not None:
                self._metrics.record_histogram(
                    "app_tpu_batch_size", len(batch), "batcher", self._name
                )
                self._metrics.set_gauge(
                    "app_tpu_queue_depth", self._queue.qsize(), "batcher", self._name
                )
            t0 = time.monotonic()
            try:
                results = self._execute([p.payload for p in batch])
                for pending, result in zip(batch, results):
                    pending.future.set_result(result)
            except Exception as exc:
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(exc)
            # Flush-time EWMA (shed Retry-After basis): failures count
            # too — a flush that burns time burns it either way.
            elapsed = time.monotonic() - t0
            self._flush_ewma_s = (
                elapsed if self._flush_ewma_s == 0.0
                else 0.8 * self._flush_ewma_s + 0.2 * elapsed
            )

    def _collect(self) -> list[_Pending]:
        """Block for the first request, then drain until size or deadline."""
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        deadline = first.enqueued_at + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.time()
            if remaining <= 0:
                break
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return batch
