"""Serving request/result types shared by the engine and its mixins.

Split from ``engine.py`` (r4 VERDICT weak #10: 3,000 lines in one
module); the engine re-exports the public names."""

from __future__ import annotations

import queue
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from gofr_tpu.serving.lifecycle import CancelToken, Deadline

if TYPE_CHECKING:  # import cycle: observability never imports types
    from gofr_tpu.serving.observability import RequestTimeline


_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


# logit_bias entries per request — the OpenAI cap. The [slots, K] planes
# upload only on admission, so K is cheap padding (~77 KB at 32 slots).
LOGIT_BIAS_K = 300


@dataclass
class GenerationResult:
    text: str
    token_ids: list[int]
    prompt_tokens: int
    ttft_s: float
    duration_s: float
    truncated: bool = False  # prompt head dropped (TPU_TRUNCATE_PROMPTS)
    # Model log-softmax at each generated token (OpenAI logprobs field).
    token_logprobs: list[float] = field(default_factory=list)
    # "stop" (eos or a stop sequence matched) | "length" (token budget or
    # context window exhausted).
    finish_reason: str = "stop"
    # True when the generation budget was clamped by the brownout
    # controller (serving/brownout.py, L1+): the truncation was a
    # deliberate overload response, not the client's max_tokens — the
    # OpenAI surface advertises it as a `brownout` field next to
    # finish_reason="length".
    brownout: bool = False
    # Per-token [(token_id, logprob), ...] alternatives when the request
    # asked for top_logprobs (None otherwise).
    token_top_logprobs: Optional[list[Optional[list[tuple[int, float]]]]] = None

    @property
    def tokens_per_sec(self) -> float:
        gen = max(len(self.token_ids), 1)
        return gen / self.duration_s if self.duration_s > 0 else 0.0


@dataclass
class _ActiveSeq:
    request: "_GenRequest"
    last_token: int
    n_generated: int = 0
    started_at: float = field(default_factory=time.time)
    first_token_at: Optional[float] = None
    # First token emitted EARLY from the prefill step's async fetch
    # (the decode window that re-emits it skips one position).
    first_emitted: bool = False
    first_skip_done: bool = False
    # Tokens already covered by dispatched windows (starts at 1: the
    # prefill-sampled first token rides the first window). When every
    # active slot's budget is in flight, dispatching more windows is
    # pure overshoot — measured at depth × window_time of wasted device
    # per retirement wave (w16d3: ~0.3 s/wave).
    tokens_in_flight: int = 1


@dataclass
class ReplayState:
    """Everything the supervisor needs to seamlessly continue a request
    on a restarted engine (``_GenRequest.replay_state``): the original
    prompt, the sampling contract, and the tokens already streamed to
    the client. The request object itself is requeued (its stream queue
    and future ARE the client's handles); this snapshot is the
    retryability decision plus the observability record of what was
    carried across the restart."""

    prompt_ids: list[int]
    emitted_ids: list[int]
    max_new_tokens: int
    temperature: float
    top_p: float
    seed: int
    stop_on_eos: bool
    stop_texts: list[str]
    # Sampling counter at snapshot time (the observability record, like
    # the rest of this snapshot): the first generated token is sampled
    # with counter 0, so after E delivered tokens the next draw must use
    # counter E. The RUNTIME restore flows through the request object —
    # ``requeue_replay`` sets ``replayed_tokens`` (== this value on the
    # fast replay path) and admission mirrors it into the per-slot
    # sample-offset plane — so a non-greedy replayed stream continues on
    # the same sample path instead of restarting at step 0.
    n_sampled: int = 0

    @property
    def remaining_tokens(self) -> int:
        """Generation budget left after the tokens already delivered."""
        return max(0, self.max_new_tokens - len(self.emitted_ids))


@dataclass
class _GenRequest:
    prompt_ids: list[int]
    max_new_tokens: int
    temperature: float
    stop_on_eos: bool
    top_p: float = 1.0
    stream: "queue.Queue[Optional[int]]" = field(default_factory=queue.Queue)
    future: Future = field(default_factory=Future)
    enqueued_at: float = field(default_factory=time.time)
    token_ids: list[int] = field(default_factory=list)
    token_logprobs: list[float] = field(default_factory=list)
    ttft_s: float = 0.0
    # Prompt length actually in the cache (set at admission; with
    # TPU_TRUNCATE_PROMPTS an overlong prompt keeps its tail and sets
    # ``truncated``; otherwise submit rejects with ErrorPromptTooLong).
    effective_prompt_len: int = 0
    truncated: bool = False
    # True → prefill only, then park the KV rows in the prefix pool and
    # resolve the future with the pool row (serving/prefix_cache.py).
    prefix_store: bool = False
    # Stop sequences: generation retires early when the decoded text
    # contains one; the result is trimmed at the match.
    stop_texts: list[str] = field(default_factory=list)
    # OpenAI-style penalties over generated tokens (TPU_PENALTIES=true).
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # Per-request sampling seed (counter-based keys: same seed + prompt +
    # params → same sampled stream regardless of batch/scheduling).
    seed: int = 0
    # OpenAI logit_bias: {token_id: bias}, at most LOGIT_BIAS_K entries.
    logit_bias: dict[int, float] = field(default_factory=dict)
    # OpenAI top_logprobs: alternatives per emitted token (≤ engine's
    # compiled TPU_TOP_LOGPROBS).
    top_logprobs: int = 0
    token_top_logprobs: list[Optional[list[tuple[int, float]]]] = field(
        default_factory=list
    )
    # Set by _finished when a stop sequence matched: char offset of the
    # earliest match in the decoded text.
    stop_cut: int = -1
    # Multi-LoRA: adapter slot index (0 = base model, no adapter) and
    # the slot's load-generation at submit time (prefix_store requests
    # whose adapter was reloaded/unloaded in flight must not register).
    # ``adapter`` is the portable NAME: slot ids are per-engine, so a
    # replica adopting this request after a failover re-resolves the
    # name against its OWN slot table (aid/lora_gen are remapped).
    aid: int = 0
    lora_gen: int = 0
    adapter: str = ""
    # Lifecycle: the scheduler's per-window reap retires the sequence
    # (and frees its KV blocks) when the deadline expires or the cancel
    # token trips — see serving/lifecycle.py and ``cancel_request``.
    deadline: Optional[Deadline] = None
    cancel: CancelToken = field(default_factory=CancelToken)
    # Admission-quota tenant (X-Tenant-Id header / gRPC metadata); ""
    # means untenanted — only the global budgets apply.
    tenant: str = ""
    # Brownout SLO class (X-SLO-Class header / x-slo-class gRPC
    # metadata, per-tenant default via TPU_TENANT_SLO_CLASS): under a
    # brownout the admission budget is consumed batch-first,
    # interactive-last (serving/brownout.py CLASS_ADMIT_FRACTION).
    slo_class: str = "standard"
    # The brownout controller clamped this request's max_new_tokens at
    # submit (L1+): the result advertises the deliberate truncation.
    brownout_clamped: bool = False
    # Times the supervisor carried this request across an engine restart,
    # and how many tokens had been delivered at the LAST replay (those
    # ride inside the re-prefilled context, so window accounting and the
    # context-length guard must not count them twice).
    replays: int = 0
    replayed_tokens: int = 0
    # Pinned to the engine it was submitted to: never handed off to a
    # sibling replica. Synthetic health probes set this — a probe that a
    # HEALTHY sibling completes would report the dead replica as alive.
    pin_replica: bool = False
    # Disaggregated-tier transfers this request has already started
    # (service/replica_pool.py): the pool refuses further exports past
    # the cap, so a request bouncing between a prefill replica and a
    # rejecting decode tier settles into fused serving instead of
    # ping-ponging forever.
    tier_hops: int = 0
    # EXACT (regeneration) replay, used for sampled streams: the engine
    # re-generates the delivered prefix from the prompt through the
    # decode path (counter-based sampling makes the walk bit-identical)
    # and the scheduler swallows this many re-generated tokens instead
    # of duplicating them on the client stream. Re-prefilling the
    # delivered tokens instead (the greedy replay path) writes their
    # K/V through the prefill kernel, which differs from the original
    # decode-written K/V by bf16 rounding — enough to flip a sampled
    # token, though never a greedy argmax.
    replay_skip: int = 0
    # Observability (serving/observability.py): the request's lifecycle
    # timeline — trace context, phase timestamps collected at window
    # granularity, replay/failover annotations. None when the layer is
    # off (TPU_FLIGHT_RECORDER=0 with no metrics and no active trace
    # exporter); every scheduler hook guards on that. The timeline rides
    # the REQUEST so a failover carries it to the adopting replica and
    # the final record covers the whole cross-replica journey.
    timeline: "Optional[RequestTimeline]" = None
    # Tenant attribution (serving/tenant_ledger.py): the ledger's own
    # clock stamps (enqueue / admission) and its exactly-once terminal
    # latch. Plain fields, not ledger-held state, so a request adopted
    # by a sibling replica after failover carries them along and the
    # adopter's ledger still attributes it exactly once.
    ledger_t0: float = 0.0
    ledger_admitted: float = 0.0
    ledger_done: bool = False

    @property
    def remaining_new_tokens(self) -> int:
        """Post-replay generation budget: ``max_new_tokens`` counts the
        client's TOTAL budget, of which ``replayed_tokens`` were already
        delivered before the restart."""
        return max(1, self.max_new_tokens - self.replayed_tokens)

    def cancel_request(self) -> None:
        """Transport-side cancel (client disconnect / explicit abort):
        trips the token the scheduler reaps on AND cancels the future so
        a not-yet-admitted request resolves immediately."""
        self.cancel.cancel()
        self.future.cancel()

    def prefill_ids(self) -> list[int]:
        """The token ids admission must prefill: the prompt plus any
        continuation tokens already delivered before an engine restart.
        A greedy replayed request re-prefills its full context so the
        next token is exactly the continuation — no client-visible
        duplicates and no gaps. An EXACT (regeneration) replay
        (``replay_skip`` > 0) prefills the prompt only: the delivered
        tokens re-generate through the decode path so their K/V — and
        therefore every later sampled token — is bit-identical. Fresh
        requests have no emitted tokens, so this is their prompt
        unchanged."""
        if self.token_ids and not self.replay_skip:
            return self.prompt_ids + self.token_ids
        return self.prompt_ids

    def retryable(self) -> bool:
        """Can this request be carried across an engine restart? False
        when already resolved, cancelled, past its deadline, or a prefix
        registration (pool rows died with the engine — the caller must
        re-register against the new one). The allocation-free predicate
        form of :meth:`replay_state` — salvage paths evaluate it per
        request under the submit lock, where copying token lists would
        hurt."""
        if self.prefix_store or self.future.done():
            return False
        if self.cancel.cancelled:
            return False
        if self.deadline is not None and self.deadline.expired():
            return False
        return True

    def replay_state(self) -> Optional[ReplayState]:
        """Snapshot for a seamless post-restart continuation, or None
        when the request is not :meth:`retryable`."""
        if not self.retryable():
            return None
        return ReplayState(
            prompt_ids=list(self.prompt_ids),
            emitted_ids=list(self.token_ids),
            max_new_tokens=self.max_new_tokens,
            temperature=self.temperature,
            top_p=self.top_p,
            seed=self.seed,
            stop_on_eos=self.stop_on_eos,
            stop_texts=list(self.stop_texts),
            # Counter-based sampling consumes exactly one step per
            # emitted token, so the delivered count IS the PRNG step.
            n_sampled=len(self.token_ids),
        )


@dataclass
class _PrefillState:
    """A slot mid-chunked-prefill (not yet decoding)."""

    request: _GenRequest
    done: int = 0  # prompt tokens already written to the cache
    # Admission-time snapshot of ``request.prefill_ids()`` (prompt plus
    # any replayed continuation) so the per-chunk dispatch loops don't
    # rebuild the concatenation once per row per iteration.
    ids: list[int] = field(default_factory=list)

