"""Serving request/result types shared by the engine and its mixins.

Split from ``engine.py`` (r4 VERDICT weak #10: 3,000 lines in one
module); the engine re-exports the public names."""

from __future__ import annotations

import queue
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

from gofr_tpu.serving.lifecycle import CancelToken, Deadline


_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


# logit_bias entries per request — the OpenAI cap. The [slots, K] planes
# upload only on admission, so K is cheap padding (~77 KB at 32 slots).
LOGIT_BIAS_K = 300


@dataclass
class GenerationResult:
    text: str
    token_ids: list[int]
    prompt_tokens: int
    ttft_s: float
    duration_s: float
    truncated: bool = False  # prompt head dropped (TPU_TRUNCATE_PROMPTS)
    # Model log-softmax at each generated token (OpenAI logprobs field).
    token_logprobs: list[float] = field(default_factory=list)
    # "stop" (eos or a stop sequence matched) | "length" (token budget or
    # context window exhausted).
    finish_reason: str = "stop"
    # Per-token [(token_id, logprob), ...] alternatives when the request
    # asked for top_logprobs (None otherwise).
    token_top_logprobs: Optional[list[Optional[list[tuple[int, float]]]]] = None

    @property
    def tokens_per_sec(self) -> float:
        gen = max(len(self.token_ids), 1)
        return gen / self.duration_s if self.duration_s > 0 else 0.0


@dataclass
class _ActiveSeq:
    request: "_GenRequest"
    last_token: int
    n_generated: int = 0
    started_at: float = field(default_factory=time.time)
    first_token_at: Optional[float] = None
    # First token emitted EARLY from the prefill step's async fetch
    # (the decode window that re-emits it skips one position).
    first_emitted: bool = False
    first_skip_done: bool = False
    # Tokens already covered by dispatched windows (starts at 1: the
    # prefill-sampled first token rides the first window). When every
    # active slot's budget is in flight, dispatching more windows is
    # pure overshoot — measured at depth × window_time of wasted device
    # per retirement wave (w16d3: ~0.3 s/wave).
    tokens_in_flight: int = 1


@dataclass
class _GenRequest:
    prompt_ids: list[int]
    max_new_tokens: int
    temperature: float
    stop_on_eos: bool
    top_p: float = 1.0
    stream: "queue.Queue[Optional[int]]" = field(default_factory=queue.Queue)
    future: Future = field(default_factory=Future)
    enqueued_at: float = field(default_factory=time.time)
    token_ids: list[int] = field(default_factory=list)
    token_logprobs: list[float] = field(default_factory=list)
    ttft_s: float = 0.0
    # Prompt length actually in the cache (set at admission; with
    # TPU_TRUNCATE_PROMPTS an overlong prompt keeps its tail and sets
    # ``truncated``; otherwise submit rejects with ErrorPromptTooLong).
    effective_prompt_len: int = 0
    truncated: bool = False
    # True → prefill only, then park the KV rows in the prefix pool and
    # resolve the future with the pool row (serving/prefix_cache.py).
    prefix_store: bool = False
    # Stop sequences: generation retires early when the decoded text
    # contains one; the result is trimmed at the match.
    stop_texts: list[str] = field(default_factory=list)
    # OpenAI-style penalties over generated tokens (TPU_PENALTIES=true).
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # Per-request sampling seed (counter-based keys: same seed + prompt +
    # params → same sampled stream regardless of batch/scheduling).
    seed: int = 0
    # OpenAI logit_bias: {token_id: bias}, at most LOGIT_BIAS_K entries.
    logit_bias: dict[int, float] = field(default_factory=dict)
    # OpenAI top_logprobs: alternatives per emitted token (≤ engine's
    # compiled TPU_TOP_LOGPROBS).
    top_logprobs: int = 0
    token_top_logprobs: list[Optional[list[tuple[int, float]]]] = field(
        default_factory=list
    )
    # Set by _finished when a stop sequence matched: char offset of the
    # earliest match in the decoded text.
    stop_cut: int = -1
    # Multi-LoRA: adapter slot index (0 = base model, no adapter) and
    # the slot's load-generation at submit time (prefix_store requests
    # whose adapter was reloaded/unloaded in flight must not register).
    aid: int = 0
    lora_gen: int = 0
    # Lifecycle: the scheduler's per-window reap retires the sequence
    # (and frees its KV blocks) when the deadline expires or the cancel
    # token trips — see serving/lifecycle.py and ``cancel_request``.
    deadline: Optional[Deadline] = None
    cancel: CancelToken = field(default_factory=CancelToken)

    def cancel_request(self) -> None:
        """Transport-side cancel (client disconnect / explicit abort):
        trips the token the scheduler reaps on AND cancels the future so
        a not-yet-admitted request resolves immediately."""
        self.cancel.cancel()
        self.future.cancel()


@dataclass
class _PrefillState:
    """A slot mid-chunked-prefill (not yet decoding)."""

    request: _GenRequest
    done: int = 0  # prompt tokens already written to the cache

