"""Model checkpoint save/restore (net-new; SURVEY §5 maps the reference's
durable-progress machinery — migrations/offsets — onto model state: the
serving engine restores params from ``TPU_CHECKPOINT`` at boot instead of
random init, and training loops snapshot params+opt state).

Backed by orbax (the TPU-ecosystem checkpointer): sharded-aware save and
restore so multi-chip params round-trip without gathering to one host.
"""

from __future__ import annotations

import os
from typing import Any


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)


def restore_checkpoint(path: str, like: Any | None = None) -> Any:
    """Restore; ``like`` (a pytree of arrays or ShapeDtypeStructs, possibly
    with shardings) guides layout + placement when given."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(path, target=like)
        return ckptr.restore(path)


def maybe_restore_params(config, params: Any, logger=None) -> Any:
    """Engine boot seam: replace random-init params with a checkpoint when
    ``TPU_CHECKPOINT`` points at one."""
    path = config.get_or_default("TPU_CHECKPOINT", "") if config is not None else ""
    if not path:
        return params
    try:
        restored = restore_checkpoint(path, like=params)
        if logger is not None:
            logger.infof("restored model params from %s", path)
        return restored
    except Exception as exc:
        if logger is not None:
            logger.errorf("could not restore checkpoint %s: %s", path, exc)
        return params
