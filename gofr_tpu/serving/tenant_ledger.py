"""Per-tenant workload attribution: the TenantLedger (ISSUE 12).

PR 6 answered "how long did this request take", PR 10 answered "how full
is this pod" — but a multi-tenant pod under pressure needs a third
answer neither floor gives: **which tenant is eating it**. This module
owns that attribution spine:

* **Token accounting** — prefill and decode tokens per tenant, summed
  exactly once per request at its terminal path (the same latched
  seams the flight recorder uses), so per-tenant totals reconcile to
  the engine's aggregate counters at any quiescent point — pinned in
  CI at ``tp=1`` AND ``tp=2``.
* **KV-block·seconds** — HBM occupancy attributed to the tenant holding
  each slot's block table, integrated once per scheduler-loop pass
  (one clock read per pass, shared by every row — graftlint GL011
  discipline, never per token). The pool-wide integral is accumulated
  in the SAME call with the SAME ``dt``, so the conservation invariant
  — Σ per-tenant block·seconds == pool-wide occupancy·seconds — holds
  *exactly*, by construction, under any clock.
* **Outcome accounting** — ok / shed / cancelled / deadline / error
  per tenant, plus queue-wait and e2e sums, so "tenant X is being shed"
  is a metric, not a grep through logs.
* **Fair-share state** — live queued requests/tokens per tenant, the
  denominator admission's fairness shed (``TPU_TENANT_FAIR_SHARE``,
  ``engine._enqueue``) divides by: a tenant holding more than its share
  of the queue budget is shed ``429 reason=tenant_fair_share`` while
  everyone else keeps being admitted.

Cardinality contract: tenant ids are request-controlled strings, so the
Prometheus export clamps to the first ``TPU_TENANT_LABEL_MAX`` distinct
tenants — later tenants fold into ``tenant="_other"`` (monotonic
counters never change label mid-flight) — while the **full unclamped
table** serves on ``/debug/tenants``. graftlint GL016
(``unbounded-metric-label``) is the static twin of this clamp: a
request-controlled string must never reach a metric label without one.

Overhead contract: with the layer off (``TPU_TENANT_LEDGER=0``) every
scheduler hook is a single ``is not None`` — the flight-recorder idiom.
With it on, the per-pass cost is one clock read, one small loop over
live slots, and dict arithmetic; nothing here touches device state.

Determinism: every timestamp is either passed in by the caller (the
scheduler's shared per-pass read) or read from the injectable ``clock``
— tests state time instead of sleeping.
"""

from __future__ import annotations


import time
from typing import Any, Callable, Iterable, Optional

from gofr_tpu.analysis import lockcheck

#: Pseudo-tenant for requests without an ``X-Tenant-Id`` — attribution
#: must be total (conservation needs every slot accounted to someone).
UNTENANTED = "_untenanted"

#: Fold bucket for tenants beyond the metric-label clamp. The full
#: unclamped table lives on ``/debug/tenants``.
OVERFLOW = "_other"

#: Bounded outcome vocabulary for ``app_tpu_tenant_requests_total``.
OUTCOMES = ("ok", "shed", "cancelled", "deadline", "error")


class _TenantStats:
    """One tenant's accumulators (mutated under the ledger lock)."""

    __slots__ = (
        "prefill_tokens", "decode_tokens", "kv_block_seconds",
        "queue_wait_s", "e2e_s", "outcomes", "queued_requests",
        "queued_tokens", "held_blocks",
    )

    def __init__(self) -> None:
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.kv_block_seconds = 0.0
        self.queue_wait_s = 0.0
        self.e2e_s = 0.0
        self.outcomes: dict[str, int] = {}
        # Live admission state (fair-share numerator).
        self.queued_requests = 0
        self.queued_tokens = 0
        # Blocks held at the last integration pass (a snapshot for the
        # debug table; the integral is what conservation pins).
        self.held_blocks = 0

    def to_dict(self) -> dict[str, Any]:
        n = sum(self.outcomes.values())
        return {
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "kv_block_seconds": round(self.kv_block_seconds, 6),
            "requests": dict(self.outcomes),
            "queue_wait_s_total": round(self.queue_wait_s, 6),
            "e2e_s_total": round(self.e2e_s, 6),
            "queued_requests": self.queued_requests,
            "queued_tokens": self.queued_tokens,
            "held_blocks": self.held_blocks,
            "requests_total": n,
        }


class TenantLedger:
    """Per-engine tenant attribution (see the module docstring)."""

    def __init__(
        self,
        model_name: str,
        *,
        metrics: Any = None,
        label_max: int = 8,
        table_max: int = 256,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.model_name = model_name
        self._metrics = metrics
        self.label_max = max(1, int(label_max))
        # The in-memory table is bounded too: tenant ids are
        # request-controlled strings, and a client minting a fresh id
        # per request must not grow ledger memory (or the scheduler
        # tick's O(tenants) pass) without bound. Past the cap, NEW
        # tenants account into the OVERFLOW row wholesale — attribution
        # stays total, the table stays O(table_max).
        self.table_max = max(self.label_max, int(table_max))
        self._clock = clock
        self._lock = lockcheck.make_lock("TenantLedger._lock")
        self._stats: dict[str, _TenantStats] = {}
        # tenant → exported metric label: its own id for the first
        # ``label_max`` distinct tenants, OVERFLOW after (stable for a
        # tenant's lifetime — counters stay monotonic per series).
        self._labels: dict[str, str] = {}
        self._last_tick: Optional[float] = None
        #: Pool-wide KV occupancy integral, accumulated in the same
        #: pass as the per-tenant shares — the conservation anchor.
        self.pool_block_seconds = 0.0

    # -- internals (call under self._lock) -----------------------------

    def _stat(self, tenant: str) -> _TenantStats:
        st = self._stats.get(tenant)
        if st is None:
            if (
                len(self._stats) >= self.table_max
                and tenant not in (UNTENANTED, OVERFLOW)
            ):
                # Table full: this tenant accounts into the overflow
                # row (bounded memory under adversarial tenant churn).
                return self._stat(OVERFLOW)
            st = _TenantStats()
            self._stats[tenant] = st
        return st

    def _label(self, tenant: str) -> str:
        """The tenant's exported metric label: its own id for the first
        ``label_max`` distinct client tenants, OVERFLOW after. Folded
        tenants are NOT stored (the dict stays O(label_max) under
        adversarial tenant churn — only own-label assignments persist,
        so every stored value equals its key)."""
        if tenant in self._labels:
            return tenant
        # The pseudo-tenants always keep their own label and never
        # consume a clamp slot — the clamp bounds CLIENT-chosen ids.
        if tenant in (UNTENANTED, OVERFLOW):
            self._labels[tenant] = tenant
            return tenant
        assigned = len([
            t for t in self._labels
            if t not in (UNTENANTED, OVERFLOW)
        ])
        if assigned < self.label_max:
            self._labels[tenant] = tenant
            return tenant
        return OVERFLOW

    @staticmethod
    def _tenant_of(req: Any) -> str:
        return str(getattr(req, "tenant", "") or "") or UNTENANTED

    def _lookup(self, tenant: str) -> Optional[_TenantStats]:
        """Read-side twin of :meth:`_stat`: an absent tenant whose row
        would have folded (table full) reads the OVERFLOW row, so
        enqueue/dequeue accounting stays balanced for folded tenants."""
        st = self._stats.get(tenant)
        if st is None and len(self._stats) >= self.table_max:
            return self._stats.get(OVERFLOW)
        return st

    # -- admission-state tracking (fair-share numerator) ----------------

    def note_enqueued(self, req: Any) -> None:
        """A request landed in the submit queue: stamp its ledger clock
        (queue-wait/e2e measurement base) and count its seat and token
        cost toward its tenant's live queue share. Called under the
        engine's submit lock (one clock read per submit)."""
        cost = len(req.prompt_ids) + int(req.max_new_tokens)
        now = self._clock()
        with self._lock:
            if req.ledger_t0 == 0.0:
                req.ledger_t0 = now
            st = self._stat(self._tenant_of(req))
            st.queued_requests += 1
            st.queued_tokens += cost

    def note_dequeued(self, req: Any) -> None:
        """The scheduler popped the request: return its seat and token
        cost to the tenant's live queue share."""
        cost = len(req.prompt_ids) + int(req.max_new_tokens)
        with self._lock:
            st = self._lookup(self._tenant_of(req))
            if st is not None:
                st.queued_requests = max(0, st.queued_requests - 1)
                st.queued_tokens = max(0, st.queued_tokens - cost)

    def reset_queued(self) -> None:
        """Drain/restart: the engine just failed or salvaged everything
        in its queue, so every tenant's live queue share is zero (the
        cumulative attribution is untouched — it survives restarts like
        the flight recorder does)."""
        with self._lock:
            for st in self._stats.values():
                st.queued_requests = 0
                st.queued_tokens = 0

    def over_fair_share(
        self,
        tenant: str,
        cost: int,
        fair_share: float,
        budget_tokens: int,
        budget_requests: int,
    ) -> bool:
        """Would admitting ``cost`` more tokens put ``tenant`` over
        ``fair_share`` of the queue budget? Token-denominated when the
        engine has a token budget (``TPU_QUEUE_TOKENS``), else
        seat-denominated against ``TPU_QUEUE_MAX``. Untenanted requests
        never trip this — fairness shedding names a culprit."""
        if fair_share <= 0 or not tenant:
            return False
        with self._lock:
            # A folded tenant shares the OVERFLOW row's queue counts:
            # fairness then applies to the overflow AGGREGATE — still
            # bounded, and a flood of fresh tenant ids cannot dodge it.
            st = self._lookup(tenant)
            queued_tokens = st.queued_tokens if st is not None else 0
            queued_requests = st.queued_requests if st is not None else 0
        if budget_tokens > 0:
            return queued_tokens + cost > fair_share * budget_tokens
        return queued_requests + 1 > fair_share * max(1, budget_requests)

    def tenant_queued_tokens(self, tenant: str) -> int:
        """The tenant's live queued token cost — the load-sensitive
        Retry-After basis for its quota/fair-share sheds (a folded
        tenant reads the OVERFLOW aggregate, same as the shed check)."""
        with self._lock:
            st = self._lookup(str(tenant or "") or UNTENANTED)
            return st.queued_tokens if st is not None else 0

    # -- scheduler hooks (window granularity) ---------------------------

    def note_admitted(self, req: Any, now: float) -> None:
        """Admission is certain: stamp the queue-wait end. ``now`` is
        the scheduler's shared per-admission clock read (the same value
        the timeline's ``mark_admitted`` gets) — no extra syscall."""
        if req.ledger_admitted == 0.0:
            req.ledger_admitted = now

    def tick(
        self, now: float, rows: Iterable[tuple[str, int]]
    ) -> None:
        """One occupancy-integration pass: ``rows`` is (tenant, blocks
        held) for every slot with a live block table, snapshotted by the
        scheduler with ONE clock read (``now``). Each tenant gains
        ``blocks × dt`` block·seconds and the pool total gains the sum —
        same ``dt``, same call, so conservation is exact."""
        flush: list[tuple[str, float]] = []
        with self._lock:
            last = self._last_tick
            self._last_tick = now
            dt = max(0.0, now - last) if last is not None else 0.0
            for st in self._stats.values():
                st.held_blocks = 0
            for tenant, blocks in rows:
                key = str(tenant or "") or UNTENANTED
                st = self._stat(key)
                st.held_blocks += int(blocks)
                if dt > 0.0 and blocks > 0:
                    share = blocks * dt
                    st.kv_block_seconds += share
                    self.pool_block_seconds += share
                    flush.append((self._label(key), share))
        if self._metrics is not None:
            for label, share in flush:
                self._metrics.add_counter(
                    "app_tpu_tenant_kv_block_seconds_total", share,
                    "model", self.model_name, "tenant", label,
                )

    # -- terminal accounting --------------------------------------------

    def finish_request(self, req: Any, outcome: str) -> None:
        """Attribute a request's totals exactly once, from whichever
        terminal path wins (retire / reap / drain / shed) — latched on
        the request under the ledger lock, the timeline-finish idiom."""
        if outcome not in OUTCOMES:
            outcome = "error"
        tenant = self._tenant_of(req)
        admitted = req.ledger_admitted > 0.0
        prefill = (
            int(req.effective_prompt_len) or len(req.prompt_ids)
        ) if admitted else 0
        decode = len(req.token_ids)
        now = self._clock()
        with self._lock:
            if req.ledger_done:
                return
            req.ledger_done = True
            st = self._stat(tenant)
            st.prefill_tokens += prefill
            st.decode_tokens += decode
            st.outcomes[outcome] = st.outcomes.get(outcome, 0) + 1
            if admitted and req.ledger_t0 > 0.0:
                st.queue_wait_s += max(
                    0.0, req.ledger_admitted - req.ledger_t0
                )
            if req.ledger_t0 > 0.0:
                st.e2e_s += max(0.0, now - req.ledger_t0)
            label = self._label(tenant)
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_tenant_requests_total",
                "model", self.model_name,
                "tenant", label, "outcome", outcome,
            )
            if prefill:
                self._metrics.add_counter(
                    "app_tpu_tenant_tokens_total", prefill,
                    "model", self.model_name,
                    "tenant", label, "phase", "prefill",
                )
            if decode:
                self._metrics.add_counter(
                    "app_tpu_tenant_tokens_total", decode,
                    "model", self.model_name,
                    "tenant", label, "phase", "decode",
                )

    # -- rendering -------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The full unclamped table (``/debug/tenants``): every tenant's
        accumulators plus the conservation anchor and the label-clamp
        state — the operator's one read for "which tenant holds the
        pool"."""
        with self._lock:
            tenants = {
                name: st.to_dict() for name, st in self._stats.items()
            }
            # Tenants with a table row but no own metric label (their
            # export folded into _other).
            folded = sorted(
                t for t in self._stats
                if t not in self._labels
                and t not in (UNTENANTED, OVERFLOW)
            )
            return {
                "enabled": True,
                "label_max": self.label_max,
                "table_max": self.table_max,
                "folded_tenants": folded,
                "pool_kv_block_seconds": round(
                    self.pool_block_seconds, 6
                ),
                "tenants": tenants,
            }

    def top_tenants(self, n: int = 5) -> list[dict[str, Any]]:
        """The ``n`` heaviest tenants by KV-block·seconds (falling back
        to decode tokens for unpaged engines) — the compact stamp that
        rides ``flight_records()`` / ``capacity_report()``."""
        with self._lock:
            ranked = sorted(
                self._stats.items(),
                key=lambda kv: (
                    kv[1].kv_block_seconds, kv[1].decode_tokens
                ),
                reverse=True,
            )[: max(1, n)]
            return [
                {
                    "tenant": name,
                    "kv_block_seconds": round(st.kv_block_seconds, 6),
                    "decode_tokens": st.decode_tokens,
                    "shed": st.outcomes.get("shed", 0),
                    "held_blocks": st.held_blocks,
                }
                for name, st in ranked
            ]
