"""Prefix-KV reuse (VERDICT r2 next #9, "prefix reuse" variant).

Shared prompt prefixes — system prompts, few-shot preambles — are
prefilled ONCE and their KV rows parked in a device-resident pool; every
later request whose prompt starts with a registered prefix admission-time
copies the pool rows into its slot and chunk-prefills only the remainder.
TTFT for a request dominated by a shared prefix drops from
O(prefix+suffix) prefill to O(suffix) plus one on-device copy.

TPU-native shape discipline: the pool is a fixed ``[L, n_entries, KV,
max_len, hd]`` buffer (same layout/dtype/sharding as the slot cache,
including int8 scale planes), and both transfers are jitted static
slices over the position axis, **bucketed** to ``_COPY_BUCKET`` multiples
so per-hit HBM traffic is O(prefix), not O(max_len) — a handful of
bucket sizes means a handful of compiles, and positions ≥ the copied
bucket are never attended (attention masks by slot length; the
remainder's prefill overwrites the boundary before it is read).

Registry ((adapter, token-tuple) → pool row + length) lives host-side
in the scheduler thread; eviction is LRU over registered prefixes.
Multi-LoRA composition: pooled K/V is a function of the weights that
prefilled it, so entries are keyed by the adapter slot id and a request
only ever reuses a prefix prefilled under its OWN adapter (base
requests match only base-prefilled prefixes); unloading an adapter
purges its entries.
"""

from __future__ import annotations


from collections import OrderedDict
from functools import partial
from typing import Any, Optional, Sequence

from gofr_tpu.analysis import lockcheck

_COPY_BUCKET = 256  # positions per copy bucket (one compile per bucket)

#: Registry key: (adapter slot id, the prefix's token ids).
_PrefixKey = tuple[int, tuple[int, ...]]


class PrefixPool:
    """Device pool of prefilled KV prefixes + host registry."""

    def __init__(self, n_entries: int, cache: Any, mesh: Any = None) -> None:
        import jax
        import jax.numpy as jnp

        self.n_entries = n_entries
        self.max_len: int = cache.max_len
        # registry: (aid, token-tuple) → pool row; ordered for LRU
        # eviction. aid is the engine's adapter slot (0 = base).
        # The lock serializes registry access: lookup/store run in the
        # scheduler thread, but purge_aid runs in whichever thread calls
        # load_lora/unload_lora.
        self._lock = lockcheck.make_lock("PrefixPool._lock")
        self._registry: "OrderedDict[_PrefixKey, int]" = OrderedDict()

        def make_pool() -> tuple[Any, ...]:
            def like(arr: Any) -> Any:
                if arr is None:
                    return None
                shape = (arr.shape[0], n_entries) + arr.shape[2:]
                return jnp.zeros(shape, arr.dtype)

            return tuple(like(a) for a in (cache.k, cache.v, cache.k_s, cache.v_s))

        if mesh is not None:
            from gofr_tpu.models.transformer import kv_cache_specs
            from gofr_tpu.parallel.sharding import named_shardings, prune_specs

            # Same pruned, cp-aware specs as the engine's cache build —
            # the pool must shard exactly like the cache it copies rows
            # with (and a cp-only mesh has no "tp" axis to name).
            specs = prune_specs(
                kv_cache_specs(
                    quantized=cache.quantized,
                    cp="cp" in mesh.axis_names,
                ),
                mesh,
            )
            shardings = tuple(
                named_shardings(s, mesh) for s in specs[:2]
            ) + ((named_shardings(specs.k_s, mesh),) * 2 if cache.quantized
                 else (None, None))
            self._pool = jax.jit(make_pool, out_shardings=shardings)()
        else:
            self._pool = make_pool()

        @partial(jax.jit, donate_argnums=(0,), static_argnums=(4,))
        def store(
            pool: Any, cache: Any, idx: Any, slot: Any, copy_len: int
        ) -> Any:
            """cache slot's first copy_len positions → pool row idx."""
            pk, pv, pks, pvs = pool
            pk = pk.at[:, idx, :, :copy_len].set(cache.k[:, slot, :, :copy_len])
            pv = pv.at[:, idx, :, :copy_len].set(cache.v[:, slot, :, :copy_len])
            if pks is not None:
                pks = pks.at[:, idx, :, :, :copy_len].set(
                    cache.k_s[:, slot, :, :, :copy_len]
                )
                pvs = pvs.at[:, idx, :, :, :copy_len].set(
                    cache.v_s[:, slot, :, :, :copy_len]
                )
            return pk, pv, pks, pvs

        @partial(jax.jit, donate_argnums=(0,), static_argnums=(4,))
        def load(
            cache: Any, pool: Any, idx: Any, slot: Any, copy_len: int
        ) -> Any:
            """pool row idx's first copy_len positions → cache slot."""
            pk, pv, pks, pvs = pool
            new = cache._replace(
                k=cache.k.at[:, slot, :, :copy_len].set(pk[:, idx, :, :copy_len]),
                v=cache.v.at[:, slot, :, :copy_len].set(pv[:, idx, :, :copy_len]),
            )
            if pks is not None:
                new = new._replace(
                    k_s=cache.k_s.at[:, slot, :, :, :copy_len].set(
                        pks[:, idx, :, :, :copy_len]
                    ),
                    v_s=cache.v_s.at[:, slot, :, :, :copy_len].set(
                        pvs[:, idx, :, :, :copy_len]
                    ),
                )
            return new

        self._store_fn = store
        self._load_fn = load

    def __len__(self) -> int:
        with self._lock:
            return len(self._registry)

    def _bucket(self, plen: int) -> int:
        b = -(-plen // _COPY_BUCKET) * _COPY_BUCKET
        return min(b, self.max_len)

    def hbm_bytes(self) -> int:
        """Device bytes of the pool's K/V (and scale) planes — the HBM
        ledger's ``prefix_pool`` component."""
        total = 0
        for arr in self._pool:
            if arr is not None:
                total += int(arr.size) * int(arr.dtype.itemsize)
        return total

    def lookup(self, ids: Sequence[int], aid: int = 0) -> tuple[int, int]:
        """Longest prefix of ``ids`` registered under adapter ``aid`` →
        (pool_row, prefix_len); (-1, 0) on miss. Hit refreshes LRU
        order."""
        best: Optional[tuple[int, ...]] = None
        ids = tuple(ids)
        with self._lock:
            for key in self._registry:
                p_aid, prefix = key
                if p_aid != aid:
                    continue
                if len(prefix) <= len(ids) and ids[: len(prefix)] == prefix:
                    if best is None or len(prefix) > len(best):
                        best = prefix
            if best is None:
                return -1, 0
            self._registry.move_to_end((aid, best))
            return self._registry[(aid, best)], len(best)

    def store(
        self, ids: Sequence[int], cache: Any, slot: int, aid: int = 0
    ) -> int:
        """Copy a just-prefilled slot's prefix rows into the pool."""
        key: _PrefixKey = (aid, tuple(ids))
        with self._lock:
            if key in self._registry:
                idx = self._registry[key]
            elif len(self._registry) < self.n_entries:
                # Rows freed by purge_aid are reusable: pick the smallest
                # row index not currently referenced.
                used = set(self._registry.values())
                idx = next(i for i in range(self.n_entries) if i not in used)
            else:  # LRU eviction
                _, idx = self._registry.popitem(last=False)
            self._pool = self._store_fn(
                self._pool, cache, idx, slot, self._bucket(len(key[1]))
            )
            self._registry[key] = idx
            self._registry.move_to_end(key)
            return idx

    def purge_aid(self, aid: int) -> int:
        """Drop every prefix registered under adapter ``aid`` (called on
        unload_lora — the slot id may be reused by a different adapter).
        Device rows stay; they are simply unreferenced. Returns count."""
        with self._lock:
            stale = [k for k in self._registry if k[0] == aid]
            for k in stale:
                del self._registry[k]
            return len(stale)

    def load(self, cache: Any, idx: int, slot: int, plen: int) -> Any:
        """Returns the cache with pool row ``idx``'s prefix copied into
        ``slot`` (O(prefix) bucketed copy)."""
        return self._load_fn(cache, self._pool, idx, slot, self._bucket(plen))
