"""TPU backend container member (net-new; SURVEY §2.6 maps it onto the
reference's datasource idiom: config-gated init in the container like
``container/container.go:81-83``, health check like ``sql/health.go:27``).

``new_tpu_from_config`` is the container seam. It is gated on ``TPU_MODEL``
so apps that don't serve models never import jax.
"""

from __future__ import annotations

from typing import Any, Optional


def new_tpu_from_config(
    config: Any, logger: Any = None, metrics: Any = None
) -> Optional[object]:
    model = config.get_or_default("TPU_MODEL", "")
    if not model:
        return None
    from gofr_tpu.serving.engine import InferenceEngine

    try:
        # Replica tier (docs/advanced-guide/resilience.md): TPU_REPLICAS
        # > 1 and/or TPU_REPLICA_ADDRS front the engine(s) with a
        # health-aware failover router — container.tpu becomes the POOL
        # (engine-shaped facade), so every serving surface routes
        # through it unchanged.
        n_replicas = int(config.get_or_default("TPU_REPLICAS", "1"))
        remote_addrs = [
            a.strip()
            for a in config.get_or_default(
                "TPU_REPLICA_ADDRS", ""
            ).split(",")
            if a.strip()
        ]
        if n_replicas > 1 or remote_addrs:
            return _new_tpu_pool_from_config(
                config, max(1, n_replicas), remote_addrs, logger, metrics
            )
        engine = InferenceEngine.from_config(config, logger=logger, metrics=metrics)
        if logger is not None:
            logger.infof("TPU backend initialised with model %s", model)
        return engine
    except Exception as exc:
        if logger is not None:
            logger.errorf("could not initialise TPU backend: %s", exc)
        return None


def _parse_replica_roles(
    config: Any, n_total: int, logger: Any
) -> list[str]:
    """``TPU_REPLICA_ROLES`` — comma-separated tier roles applied
    positionally across the pool's replicas (in-proc engines first,
    then remote addresses); replicas past the list's end default to
    ``fused``. ``"prefill,decode"`` is the canonical disaggregated
    pair. Unknown role names fail construction loudly — silently
    serving fused under a typo'd topology would defeat the operator's
    explicit disaggregation."""
    raw = config.get_or_default("TPU_REPLICA_ROLES", "")
    roles = [r.strip().lower() for r in raw.split(",") if r.strip()]
    for role in roles:
        if role not in ("fused", "prefill", "decode"):
            raise ValueError(
                f"TPU_REPLICA_ROLES entry {role!r} is not one of "
                f"fused|prefill|decode"
            )
    if roles and len(roles) > n_total and logger is not None:
        logger.warnf(
            "TPU_REPLICA_ROLES names %d role(s) but the pool has %d "
            "replica(s); extras ignored", len(roles), n_total,
        )
    return (roles + ["fused"] * n_total)[:n_total]


def _new_tpu_pool_from_config(
    config: Any,
    n_replicas: int,
    remote_addrs: list,
    logger: Any,
    metrics: Any,
) -> Any:
    """Build the replica pool: N in-process engines (each with its own
    supervisor when TPU_RESTART_MAX is set) plus one HTTPReplica per
    remote address, fronted by a ReplicaPool with the probe/hedge knobs
    (TPU_PROBE_INTERVAL_S / TPU_PROBE_TIMEOUT_S / TPU_HEDGE_DELAY_S /
    TPU_HEDGE_BUDGET). In-proc replicas share the same config — same
    params and engine seed — so cross-replica replay continues streams
    byte-identically.

    TPU_REPLICA_ROLES splits the pool into disaggregated prefill/
    decode tiers (docs/advanced-guide/resilience.md): prefill replicas
    ship finished KV blocks to decode replicas, budgeted by
    TPU_TRANSFER_RETRIES / TPU_TRANSFER_TIMEOUT_S, and every failure
    degrades back to fused serving."""
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.lifecycle import HedgeBudget
    from gofr_tpu.service import new_http_service
    from gofr_tpu.service.pool_scaler import PoolScaler
    from gofr_tpu.service.replica_pool import (
        EngineReplica,
        HTTPReplica,
        ReplicaPool,
    )

    def truthy(key: str, default: str) -> bool:
        return config.get_or_default(key, default).lower() in (
            "1", "true", "yes",
        )

    roles = _parse_replica_roles(
        config, n_replicas + len(remote_addrs), logger
    )
    if any(r != "fused" for r in roles):
        # Tier transfers ship paged blocks into the importer's radix
        # index: without TPU_KV_BLOCK + TPU_AUTO_PREFIX the tier still
        # WORKS (requests re-prefill on the decode replica — fused
        # import), it just never gets the saved prefill. Say so once at
        # boot instead of letting the operator chase a silent perf gap.
        if logger is not None and (
            int(config.get_or_default("TPU_KV_BLOCK", "0")) <= 0
            or not truthy("TPU_AUTO_PREFIX", "false")
        ):
            logger.warnf(
                "TPU_REPLICA_ROLES set without TPU_KV_BLOCK>0 + "
                "TPU_AUTO_PREFIX=true: tier transfers will re-prefill "
                "on the decode tier instead of aliasing shipped KV "
                "blocks"
            )

    # GSPMD pods (TPU_TP > 1 / TPU_MESH_CP > 1): each in-proc replica
    # becomes ONE sharded pod over its own DISJOINT slice of the device
    # list — dp across replicas, tp (× cp) within each. Without enough
    # devices to cover every replica disjointly, overflow replicas
    # share the first slice (correct, just without the parallel
    # speedup) and the shortfall is logged once instead of the operator
    # chasing a silent perf gap.
    tp = int(
        config.get_or_default(
            "TPU_TP", config.get_or_default("TPU_MESH_TP", "1")
        )
    )
    cp = int(config.get_or_default("TPU_MESH_CP", "1"))
    pod_size = max(1, tp) * max(1, cp)
    device_groups: list = [None] * n_replicas
    if pod_size > 1:
        import jax

        from gofr_tpu.parallel.mesh import partition_devices

        all_devices = list(jax.devices())
        if len(all_devices) < pod_size:
            # Not even ONE pod fits: fail at the seam with the real
            # arithmetic instead of letting make_mesh crash after a
            # log line that promised degraded boot.
            raise ValueError(
                f"sharded pool: one pod needs tp·cp={pod_size} "
                f"device(s) but only {len(all_devices)} are visible — "
                f"lower TPU_TP/TPU_MESH_CP or add devices"
            )
        if len(all_devices) < pod_size * n_replicas and logger is not None:
            logger.warnf(
                "sharded pool wants %d devices (%d replica(s) × tp·cp="
                "%d) but only %d are visible: replicas past the last "
                "full slice share the first slice's devices",
                pod_size * n_replicas, n_replicas, pod_size,
                len(all_devices),
            )
        device_groups = partition_devices(
            all_devices, pod_size, n_replicas
        )

    replicas: list = []
    for i in range(n_replicas):
        engine = InferenceEngine.from_config(
            config, logger=logger, metrics=metrics,
            devices=device_groups[i],
        )
        replicas.append(
            EngineReplica(f"engine-{i}", engine, role=roles[i])
        )
    # Remote replicas stream by default (TPU_REMOTE_STREAM): the pool
    # consumes the remote's SSE with the include_tokens extension, so
    # streaming requests route to remote pods and a remote that dies
    # mid-stream fails over to a sibling. They share the in-proc
    # tokenizer (same model across the pool) so string prompts encode
    # locally and the delivered-token prefix is reconstructable.
    remote_stream = truthy("TPU_REMOTE_STREAM", "true")
    shared_tokenizer = next(
        (r.engine.tokenizer for r in replicas), None
    )
    # Wire-leg tier transfers (TPU_REPLICA_OPS_ADDRS, positional like
    # TPU_REPLICA_ADDRS): each remote's OPS/metrics address hosts the
    # POST /ops/tier-import endpoint — with one configured, a remote
    # decode replica can adopt shipped KV blocks over the wire instead
    # of forcing the fused fallback. Empty entries leave that replica
    # wire-import-incapable (unary remotes, older pods).
    ops_addrs = [
        a.strip()
        for a in config.get_or_default("TPU_REPLICA_OPS_ADDRS", "").split(",")
    ] if config.get_or_default("TPU_REPLICA_OPS_ADDRS", "") else []
    for j, addr in enumerate(remote_addrs):
        ops_addr = ops_addrs[j] if j < len(ops_addrs) else ""
        replicas.append(
            HTTPReplica(
                addr,
                new_http_service(addr, logger, metrics),
                stream=remote_stream,
                tokenizer=shared_tokenizer,
                idle_timeout_s=float(
                    config.get_or_default("TPU_REMOTE_STREAM_IDLE_S", "30")
                ),
                role=roles[n_replicas + j],
                import_service=(
                    new_http_service(ops_addr, logger, metrics)
                    if ops_addr else None
                ),
                metrics=metrics,
                logger=logger,
            )
        )
    pool = ReplicaPool(
        replicas,
        hedge_delay_s=float(
            config.get_or_default("TPU_HEDGE_DELAY_S", "2.0")
        ),
        hedge_budget=HedgeBudget(
            burst=float(config.get_or_default("TPU_HEDGE_BUDGET", "8")),
            rate_per_s=float(
                config.get_or_default("TPU_HEDGE_RATE_PER_S", "2")
            ),
        ),
        probe_interval_s=float(
            config.get_or_default("TPU_PROBE_INTERVAL_S", "30")
        ),
        probe_timeout_s=float(
            config.get_or_default("TPU_PROBE_TIMEOUT_S", "30")
        ),
        # Weighted routing: least-estimated-completion-time over the
        # per-replica measured tokens/sec; false = raw queue length.
        weighted=config.get_or_default(
            "TPU_ROUTE_WEIGHTED", "true"
        ).lower() in ("1", "true", "yes"),
        # Tier-transfer budget: extra import attempts past the first
        # and the transfer-wide wall-clock bound.
        transfer_retries=int(
            config.get_or_default("TPU_TRANSFER_RETRIES", "2")
        ),
        transfer_timeout_s=float(
            config.get_or_default("TPU_TRANSFER_TIMEOUT_S", "10")
        ),
        # Leg pin (default: automatic dma → device → wire → host
        # ladder).
        transfer_leg=config.get_or_default("TPU_TRANSFER_LEG", ""),
        # Remote prefill-source pull budget (0 disables the pull
        # plane).
        source_timeout_s=float(
            config.get_or_default("TPU_SOURCE_TIMEOUT_S", "2.0")
        ),
        metrics=metrics,
        logger=logger,
    )
    # Load-adaptive scaling (docs/advanced-guide/resilience.md):
    # TPU_POOL_MAX_REPLICAS above the configured fleet arms a PoolScaler
    # that spawns in-proc engine replicas under sustained queue pressure
    # and drains them (stop-routing → bounded completion → retire) when
    # idle. Bounds: TPU_POOL_MIN_REPLICAS / TPU_POOL_MAX_REPLICAS;
    # sustain windows: TPU_SCALE_UP_WAIT_S / TPU_SCALE_DOWN_WAIT_S.
    max_replicas = int(config.get_or_default("TPU_POOL_MAX_REPLICAS", "0"))
    if max_replicas > len(replicas):
        counter = [len(replicas)]

        def spawn_engine_replica() -> Any:
            # Scaled pods land on a device slice no LIVE in-proc
            # replica currently holds (remote replicas consume no local
            # devices, and a drained replica's slice frees for reuse) —
            # a spawn counter would double-occupy slice 0 while free
            # slices sat idle. Only past the last free slice does a
            # spawn share slice 0, mirroring the boot-time fallback.
            spawn_devices = None
            if pod_size > 1:
                import jax

                from gofr_tpu.parallel.mesh import partition_devices

                all_devices = list(jax.devices())
                slices = partition_devices(
                    all_devices, pod_size,
                    max(1, len(all_devices) // pod_size),
                )
                held = set()
                for replica in pool.replicas:
                    mesh = getattr(
                        getattr(replica, "engine", None), "mesh", None
                    )
                    if mesh is not None:
                        held.add(frozenset(
                            str(d) for d in mesh.devices.flat
                        ))
                spawn_devices = next(
                    (
                        s for s in slices
                        if frozenset(str(d) for d in s) not in held
                    ),
                    slices[0],
                )
            engine = InferenceEngine.from_config(
                config, logger=logger, metrics=metrics,
                devices=spawn_devices,
            )
            engine.start_sync()
            counter[0] += 1
            return EngineReplica(f"engine-scaled-{counter[0]}", engine)

        pool.scaler = PoolScaler(
            pool,
            spawn_engine_replica,
            min_replicas=int(config.get_or_default(
                "TPU_POOL_MIN_REPLICAS", str(len(replicas))
            )),
            max_replicas=max_replicas,
            up_load_per_replica=float(config.get_or_default(
                "TPU_SCALE_UP_LOAD", "4"
            )),
            down_load_per_replica=float(config.get_or_default(
                "TPU_SCALE_DOWN_LOAD", "0.5"
            )),
            # Saturation-aware scale-up (device_telemetry headroom):
            # a serving replica below this HBM headroom ratio counts
            # as pressure even with a shallow queue. 0 = off.
            up_headroom_floor=float(config.get_or_default(
                "TPU_SCALE_UP_HEADROOM", "0"
            )),
            # Brownout-aware scale-up (serving/brownout.py): a replica
            # holding L2+ is shedding admissions — that is demand, not
            # idleness. Default on; the signal only exists when the
            # brownout layer is armed.
            up_on_brownout=config.get_or_default(
                "TPU_SCALE_UP_BROWNOUT", "1"
            ).lower() not in ("0", "false", "no"),
            # Control-plane scale-up (serving/control_plane.py): a
            # replica whose host-overhead or predictive loop holds
            # scale pressure is asking for capacity BEFORE the queue
            # shows it. Default on; the signal only exists when
            # TPU_CONTROL_PLANE is armed.
            up_on_control=config.get_or_default(
                "TPU_SCALE_UP_CONTROL", "1"
            ).lower() not in ("0", "false", "no"),
            scale_up_wait_s=float(config.get_or_default(
                "TPU_SCALE_UP_WAIT_S", "10"
            )),
            scale_down_wait_s=float(config.get_or_default(
                "TPU_SCALE_DOWN_WAIT_S", "60"
            )),
            interval_s=float(config.get_or_default(
                "TPU_SCALE_INTERVAL_S", "5"
            )),
            metrics=metrics,
            logger=logger,
        )
    if logger is not None:
        logger.infof(
            "TPU replica pool initialised: %d in-proc engine(s), %d "
            "remote replica(s)%s", n_replicas, len(remote_addrs),
            (
                f", scaler armed ({pool.scaler.min_replicas}-"
                f"{pool.scaler.max_replicas} replicas)"
                if pool.scaler is not None else ""
            ),
        )
    return pool


def new_tpu_embed_from_config(
    config: Any, logger: Any = None, metrics: Any = None
) -> Optional[object]:
    """Secondary encoder engine (``TPU_EMBED_MODEL``) so one app can serve
    chat from the primary engine AND /v1/embeddings from an encoder —
    the same config-gated datasource idiom as the primary."""
    model = config.get_or_default("TPU_EMBED_MODEL", "")
    if not model:
        return None
    from gofr_tpu.models.registry import get_model
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer, tokenizer_from_config

    try:
        spec = get_model(model)
        if spec.family != "encoder":
            raise ValueError(
                f"TPU_EMBED_MODEL={model!r} is family {spec.family!r}, "
                f"need an encoder (e.g. bert-base)"
            )
        # The encoder needs its OWN vocabulary — the chat model's
        # TPU_TOKENIZER would feed llama-range ids into the BERT
        # embedding table (XLA clamps the gather silently → garbage).
        tok_path = config.get_or_default("TPU_EMBED_TOKENIZER", "")
        if tok_path:
            tok_config = _Overlay(config, {"TPU_TOKENIZER": tok_path})
            tokenizer = tokenizer_from_config(tok_config, logger)
        else:
            tokenizer = ByteTokenizer()
        engine = InferenceEngine(
            model,
            max_batch=int(config.get_or_default("TPU_MAX_BATCH", "8")),
            max_wait_s=float(
                config.get_or_default("TPU_BATCH_WAIT_MS", "5")
            ) / 1e3,
            max_len=int(config.get_or_default("TPU_MAX_LEN", "1024")),
            logger=logger,
            metrics=metrics,
            tokenizer=tokenizer,
        )
        ckpt = config.get_or_default("TPU_EMBED_CHECKPOINT", "")
        if ckpt:
            from gofr_tpu.serving.checkpoint import restore_checkpoint

            engine.params = restore_checkpoint(ckpt, like=engine.params)
            if logger is not None:
                logger.infof("restored embed params from %s", ckpt)
        if logger is not None:
            logger.infof("TPU embed backend initialised with model %s", model)
        return engine
    except Exception as exc:
        if logger is not None:
            logger.errorf("could not initialise TPU embed backend: %s", exc)
        return None


class _Overlay:
    """Config view with a few keys overridden (keeps the Config protocol)."""

    def __init__(self, base: Any, overrides: dict) -> None:
        self._base, self._overrides = base, overrides

    def get(self, key: str) -> Any:
        if key in self._overrides:
            return self._overrides[key]
        return self._base.get(key)

    def get_or_default(self, key: str, default: str) -> Any:
        if key in self._overrides:
            return self._overrides[key]
        return self._base.get_or_default(key, default)
