"""TPU backend container member (net-new; SURVEY §2.6 maps it onto the
reference's datasource idiom: config-gated init in the container like
``container/container.go:81-83``, health check like ``sql/health.go:27``).

``new_tpu_from_config`` is the container seam. It is gated on ``TPU_MODEL``
so apps that don't serve models never import jax.
"""

from __future__ import annotations

from typing import Optional


def new_tpu_from_config(config, logger=None, metrics=None) -> Optional[object]:
    model = config.get_or_default("TPU_MODEL", "")
    if not model:
        return None
    from gofr_tpu.serving.engine import InferenceEngine

    try:
        engine = InferenceEngine.from_config(config, logger=logger, metrics=metrics)
        if logger is not None:
            logger.infof("TPU backend initialised with model %s", model)
        return engine
    except Exception as exc:
        if logger is not None:
            logger.errorf("could not initialise TPU backend: %s", exc)
        return None
