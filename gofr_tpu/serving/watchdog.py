"""Scheduler watchdog: declare the engine unhealthy when it stalls.

A hung device step (wedged relay, deadlocked collective, runaway
compile) is indistinguishable from a slow one from inside the
scheduler thread — it is *blocked*. The watchdog watches from outside:
the scheduler **pets** it once per loop iteration (idle iterations pet
every ≤20 ms, busy ones once per window), and a monitor checks that
the gap since the last pet stays under a configurable wall-time bound.

On a trip the watchdog latches unhealthy, bumps
``app_tpu_watchdog_trips_total``, opens a tracing span so the stall is
visible in traces, and invokes ``on_trip`` — the engine's callback
flips it into draining (new submissions get 503) and the health
endpoint reports DOWN; with a supervisor attached
(``serving/supervisor.py``) the callback also requests an automatic
restart. The latch clears only on engine restart — manual or
supervisor-driven; either path runs ``reset()`` + ``start()`` on this
SAME instance, so the monitor thread (which exits once latched) is
respawned and the restarted engine is watched from a fresh pet
baseline.

Determinism: ``check(now=...)`` takes an explicit timestamp, so tests
trip the watchdog by *stating* a time, not by sleeping through the
bound. The background monitor thread (production) is just
``check()`` on an ``Event.wait`` cadence.
"""

from __future__ import annotations

import threading

import time
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck


class Watchdog:
    """Wall-clock progress monitor for the scheduler thread."""

    def __init__(
        self,
        bound_s: float,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_trip: Optional[Callable[[str], None]] = None,
        metrics: Any = None,
        logger: Any = None,
        model_name: str = "",
        check_interval_s: Optional[float] = None,
    ) -> None:
        self.bound_s = float(bound_s)
        self._clock = clock
        self._on_trip = on_trip
        self._metrics = metrics
        self._logger = logger
        self._model_name = model_name
        # Check often enough that a trip is reported well inside 2×bound
        # without burning a core.
        self._interval = (
            check_interval_s
            if check_interval_s is not None
            else max(0.05, min(self.bound_s / 4.0, 1.0))
        )
        self._lock = lockcheck.make_lock("Watchdog._lock")
        self._last_pet = self._clock()
        self._tripped = False
        self._reason = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- scheduler side -------------------------------------------------

    def pet(self) -> None:
        """Progress heartbeat; called once per scheduler loop iteration."""
        # Single float store (GIL-atomic); the monitor tolerates a torn
        # read's staleness of one iteration.
        self._last_pet = self._clock()

    # -- monitor side ---------------------------------------------------

    @property
    def tripped(self) -> bool:
        return self._tripped

    @property
    def reason(self) -> str:
        return self._reason

    def check(self, now: Optional[float] = None) -> bool:
        """Evaluate the bound; returns the (possibly just-latched)
        tripped state. ``now`` overrides the clock for deterministic
        tests."""
        if self._tripped:
            return True
        t = self._clock() if now is None else now
        stalled_for = t - self._last_pet
        if stalled_for > self.bound_s:
            self._trip(
                f"scheduler made no progress for {stalled_for:.1f}s "
                f"(bound {self.bound_s:.1f}s)"
            )
        return self._tripped

    def _trip(self, reason: str) -> None:
        with self._lock:
            if self._tripped:
                return
            self._tripped = True
            self._reason = reason
        if self._logger is not None:
            self._logger.errorf("watchdog tripped: %s", reason)
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_watchdog_trips_total", "model", self._model_name
            )
        # Tracing: a zero-child span marks the trip instant so the stall
        # is findable next to the request spans it wedged.
        try:
            from gofr_tpu.tracing import get_tracer

            span = get_tracer().start_span("tpu-watchdog-trip")
            span.set_attribute("reason", reason)
            span.set_status("ERROR")
            span.end()
        except Exception as exc:  # noqa: BLE001 — tracing must not mask the trip
            if self._logger is not None:
                self._logger.debugf("watchdog trace span failed: %s", exc)
        if self._on_trip is not None:
            self._on_trip(reason)

    def reset(self) -> None:
        """Clear the latch (engine restart)."""
        with self._lock:
            self._tripped = False
            self._reason = ""
        self._last_pet = self._clock()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._last_pet = self._clock()
        self._thread = threading.Thread(
            target=self._monitor_loop, name="tpu-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.check()
            if self._tripped:
                # Latched; nothing more to observe until reset.
                return
