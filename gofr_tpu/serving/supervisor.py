"""Engine supervision: self-healing restarts with crash-loop backoff.

PR 2's watchdog turned a wedged device step into a *detected* failure —
but a detected failure still latched the engine DOWN until an operator
restarted it. A production jax_graft system serving millions of users
must survive a hung relay or a crashed scheduler loop without a pager:
GoFr's capability surface implies the FRAMEWORK owns recovery, and the
north star's ICI-sharded multi-chip serving makes single-replica
self-healing the prerequisite for any replica-level failover story.

:class:`EngineSupervisor` owns the restart policy the engine itself
deliberately does not have:

* **Detection** — the watchdog's trip callback and the scheduler's
  fatal-exit path both notify the supervisor (``notify_trip`` /
  ``notify_crash``) instead of being terminal.
* **Salvage** — still-live *retryable* sequences (not cancelled, not
  past deadline, not prefix registrations) are snapshotted via
  ``_GenRequest.replay_state()`` — prompt, sampling params, and the
  tokens already streamed — and parked instead of failed. Their stream
  queues and futures stay open: the client never sees the crash.
* **Teardown + warm restart** — the engine's per-boot serving state
  (KV cache, paged allocator, queues, device slot planes) is rebuilt by
  ``engine.restart_sync()`` while the already-loaded params pytree and
  the compiled programs are reused — recovery costs a cache allocation,
  not a model load. A scheduler thread that never exits (truly wedged
  device call) is *abandoned*: the engine's scheduler epoch is bumped so
  every later touch from the zombie raises ``SchedulerSuperseded``
  instead of corrupting the fresh scheduler's state.
* **Backoff** — restarts are crash-loop aware: exponential, jittered
  (``TPU_RESTART_BACKOFF_S`` base, injectable clock/rng so tests state
  time instead of sleeping), with the consecutive-failure counter
  resetting after a stable period. ``TPU_RESTART_MAX`` consecutive
  failures land the engine in DOWN rather than restarting forever.
* **Replay** — after a successful restart the salvaged requests requeue
  (``engine.requeue_replay``): admission re-prefills prompt + the
  already-delivered tokens, so an SSE stream resumes at exactly the
  next token — no duplicates, no gaps. Requests that stopped being
  retryable during the restart get the existing terminal error event.

Health state machine, surfaced through ``engine.health_check`` (and so
``/.well-known/health`` and both gRPC Health RPCs) plus the
``app_tpu_engine_state`` gauge::

    SERVING ──trip/crash──▶ DEGRADED ──supervisor──▶ RESTARTING
       ▲                                                 │
       └───────── restart + replay succeeded ────────────┤
                                                         ▼
                DOWN ◀── TPU_RESTART_MAX consecutive failures

Observability: ``app_tpu_engine_restarts_total`` and
``app_tpu_requests_replayed_total`` count recoveries and carried
requests; every transition logs with its reason.
"""

from __future__ import annotations

import queue
import random
import threading

import time
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck
from gofr_tpu.serving.types import _GenRequest

#: State-machine order mirrored into the ``app_tpu_engine_state`` gauge.
STATES = ("SERVING", "DEGRADED", "RESTARTING", "DOWN")


class EngineSupervisor:
    """Owns one engine's restart policy (attach via construction).

    All timing seams are injectable — ``clock`` for the stability
    window, ``rng`` for jitter, ``sleep`` for the backoff wait — so the
    chaos suite drives every recovery path deterministically: no real
    sleeps, no wall-clock races.
    """

    def __init__(
        self,
        engine: Any,
        *,
        max_restarts: int = 5,
        backoff_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        backoff_reset_s: float = 60.0,
        join_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        sleep: Optional[Callable[[float], None]] = None,
        metrics: Any = None,
        logger: Any = None,
    ) -> None:
        self._engine = engine
        self.max_restarts = max(1, int(max_restarts))
        self.backoff_s = max(0.0, float(backoff_s))
        self.backoff_cap_s = max(self.backoff_s, float(backoff_cap_s))
        self.backoff_reset_s = float(backoff_reset_s)
        self.join_timeout_s = float(join_timeout_s)
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._metrics = metrics
        self._logger = logger

        self._lock = lockcheck.make_lock("EngineSupervisor._lock")
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        # Default backoff wait doubles as the stop latch: a shutdown
        # mid-backoff returns immediately instead of finishing the wait.
        self._sleep: Callable[[float], None] = (
            sleep if sleep is not None else self._default_sleep
        )
        self._pending_reason: Optional[str] = None  # graftlint: guarded-by=_lock
        self._stopping = False  # graftlint: guarded-by=_lock
        self._thread: Optional[threading.Thread] = None

        # Policy bookkeeping (supervisor-thread-owned after start()).
        self.restarts = 0  # successful warm restarts performed
        self._consecutive = 0  # failures since the last stable period
        self._last_recovered_at: Optional[float] = None

        engine.attach_supervisor(self)

    def _default_sleep(self, seconds: float) -> None:
        self._stop_evt.wait(seconds)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "EngineSupervisor":
        if self._thread is not None and self._thread.is_alive():
            return self
        # Under the lock like every other _stopping write: a lock-free
        # reset here could interleave into a concurrent stop() between
        # its flag write and its event set, resurrecting a supervisor
        # the operator is tearing down (GL020's first real catch).
        with self._lock:
            self._stopping = False
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpu-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop supervising (engine close / app shutdown). Does NOT stop
        the engine — by this point the caller owns its lifecycle again.
        Requests a recovery parked for replay are failed with an
        explicit shutdown error: nothing will ever requeue them, and a
        stopped supervisor must not leave clients hanging on open
        streams/futures."""
        with self._lock:
            self._stopping = True
        self._stop_evt.set()
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self.drain_parked()

    @property
    def stopping(self) -> bool:
        """True once stop() began: the scheduler's death drain consults
        this — a stopping supervisor accepts no salvage, because nothing
        would ever requeue it. Lock-free read: the flag only ever
        latches False→True while the reader cares, and the scheduler's
        death drain must not contend on the supervisor's lock."""
        return self._stopping  # graftlint: disable=GL020 — monotonic latch read; GIL-atomic bool, stale False only delays the drain one poll

    def drain_parked(self) -> None:
        """Pop-and-fail everything parked for replay (idempotent: pops
        under the submit lock, so stop(), a racing recovery's own
        stop-path, and engine.close()'s final sweep each fail a request
        at most once)."""
        from gofr_tpu.errors import ErrorServiceUnavailable

        eng = self._engine
        with eng._submit_lock:
            reqs, eng._replay = list(eng._replay), []
        if not reqs:
            return
        exc = ErrorServiceUnavailable(
            "engine shutting down mid-recovery; retry against another "
            "replica"
        )
        for req in reqs:
            self._fail_request(req, exc)

    # -- notifications (watchdog thread / dying scheduler thread) -------

    def notify_trip(self, reason: str) -> None:
        """Watchdog trip: the scheduler is WEDGED (it may never exit)."""
        self._request_recovery(f"watchdog: {reason}")

    def notify_crash(self, exc: BaseException) -> None:
        """Fatal scheduler exit: the thread drained (salvaging retryable
        requests into the engine's replay list) and died."""
        self._request_recovery(f"scheduler crash: {exc}")

    def notify_probe_failure(self, reason: str) -> None:
        """A synthetic health probe failed against a replica that still
        CLAIMS to be serving (replica pool's active prober): the serving
        dataplane is broken in a way no crash or watchdog trip caught —
        treat it as a detected failure and restart, instead of waiting
        for a real request to wedge. Degrades first so health endpoints
        and the pool's router stop sending traffic immediately."""
        self._engine._set_state("DEGRADED")
        self._request_recovery(f"probe: {reason}")

    def note_probe_success(self) -> None:
        """A synthetic probe PASSED (pool prober): the engine provably
        serves end to end, so the crash-loop window closes — the
        consecutive-failure counter resets and the next failure starts a
        fresh restart budget rather than landing straight in DOWN."""
        self._consecutive = 0
        self._last_recovered_at = self._clock()

    def revive(self) -> bool:
        """Bring a DOWN engine back for probation (probe-driven
        re-admission): restart it with a FRESH crash-loop budget. The
        caller (the pool's prober) must follow with a passing synthetic
        probe before routing traffic again — revive restores the
        machinery, the probe earns re-admission. Returns False when the
        supervisor is stopping or the restart itself fails (the engine
        stays DOWN)."""
        with self._lock:
            if self._stopping:
                return False
        try:
            self._engine.restart_sync()
        except Exception as exc:  # noqa: BLE001 — a failed revive must report, not raise
            if self._logger is not None:
                self._logger.errorf(
                    "supervisor: revive failed; engine stays DOWN: %s", exc
                )
            try:
                self._engine.stop_sync()
            except Exception:  # graftlint: disable=GL006 — best-effort rollback; the revive failure above is already logged
                pass
            return False
        self._consecutive = 0
        self._last_recovered_at = self._clock()
        if self._logger is not None:
            self._logger.infof(
                "supervisor: engine revived from DOWN (probe-driven); "
                "restart budget reset"
            )
        return True

    def _request_recovery(self, reason: str) -> None:
        with self._lock:
            if self._stopping:
                return
            # Coalesce: one recovery handles however many signals raced
            # in (a trip often precedes the wedged step's eventual
            # raise); keep the FIRST reason — it named the root cause.
            if self._pending_reason is None:
                self._pending_reason = reason
        self._wake.set()

    # -- introspection --------------------------------------------------

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive

    def describe(self) -> dict:
        """Health-endpoint block (rides engine.health_check details)."""
        return {
            "restarts": self.restarts,
            "consecutive_failures": self._consecutive,
            "max_restarts": self.max_restarts,
            "backoff_s": self.backoff_s,
        }

    def backoff_delay(self, attempt: int) -> float:
        """Jittered exponential backoff for the ``attempt``-th
        consecutive restart (0-based): ``backoff_s * 2^attempt`` capped
        at ``backoff_cap_s``, scaled into [50%, 100%] so a fleet of
        replicas does not restart in lockstep."""
        base = min(self.backoff_cap_s, self.backoff_s * (2.0 ** attempt))
        return base * (0.5 + 0.5 * self._rng.random())

    # -- the supervision loop -------------------------------------------

    def _loop(self) -> None:
        while True:
            self._wake.wait()
            with self._lock:
                if self._stopping:
                    return
                reason = self._pending_reason
                self._pending_reason = None
                self._wake.clear()
            if reason is None:
                continue
            try:
                self._recover(reason)
            except Exception as exc:  # noqa: BLE001 — the loop must survive
                # A recovery step itself failing (cache realloc OOM on a
                # sick device, teardown error) must not kill this thread:
                # a dead supervisor strands every parked request forever.
                # Land in DOWN — the terminal state whose contract is
                # "every parked caller gets an explicit error".
                if self._logger is not None:
                    self._logger.errorf(
                        "supervisor: recovery itself failed (%s); "
                        "declaring the engine DOWN", exc,
                    )
                try:
                    self._give_up(f"recovery failed: {exc}")
                except Exception as exc2:  # noqa: BLE001 — last resort
                    if self._logger is not None:
                        self._logger.errorf(
                            "supervisor: give-up also failed: %s", exc2
                        )

    def _recover(self, reason: str) -> None:
        eng = self._engine
        now = self._clock()
        if (
            self._last_recovered_at is not None
            and now - self._last_recovered_at > self.backoff_reset_s
        ):
            # The previous recovery held long enough to count as stable:
            # this failure starts a fresh crash-loop window.
            self._consecutive = 0
        if self._consecutive >= self.max_restarts:
            self._give_up(reason)
            return
        attempt = self._consecutive
        self._consecutive += 1
        if self._logger is not None:
            self._logger.errorf(
                "supervisor: engine failure (%s); restart attempt %d/%d",
                reason, attempt + 1, self.max_restarts,
            )
        eng._set_state("RESTARTING")
        self._teardown()
        # Signals that raced in during teardown describe the SAME failure
        # being recovered (a trip's wedged step often raises moments
        # later; the old scheduler is dead and the new one not yet
        # started, so nothing else can be failing): absorb them so one
        # fault never burns two restart attempts.
        with self._lock:
            self._pending_reason = None
        # The three bail-out probes below read the stop latch lock-free
        # on purpose: each sits before/after a long blocking step
        # (backoff sleep, cache realloc) and a stale False merely means
        # stop()'s own drain_parked sweep — idempotent — cleans up.
        if self._stopping:  # graftlint: disable=GL020 — monotonic latch probe; stop() re-drains idempotently
            self.drain_parked()
            return
        self._sleep(self.backoff_delay(attempt))
        if self._stopping:  # graftlint: disable=GL020 — monotonic latch probe; stop() re-drains idempotently
            self.drain_parked()
            return
        eng.restart_sync()
        if self._stopping:  # graftlint: disable=GL020 — monotonic latch probe; stop() re-drains idempotently
            # close() raced the restart (its join timed out while the
            # cache realloc ran): undo the resurrection — the operator
            # asked for a stopped engine — and fail whatever was parked
            # (idempotent with stop()'s own drain).
            eng.stop_sync()
            self.drain_parked()
            return
        self.restarts += 1
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_engine_restarts_total",
                "model", eng.model_name,
            )
        replayed, dropped = self._requeue_salvaged()
        self._last_recovered_at = self._clock()
        if self._logger is not None:
            self._logger.infof(
                "supervisor: engine restarted (attempt %d); %d request(s) "
                "replayed, %d no longer retryable",
                attempt + 1, replayed, dropped,
            )

    def _teardown(self) -> None:
        """Stop the failed scheduler WITHOUT the engine's long join: mark
        a restart pending (the dying thread's drain then salvages
        retryable requests instead of failing them), give the thread a
        bounded join, and abandon it if it is truly wedged — bumping the
        scheduler epoch so any later touch from the zombie raises
        ``SchedulerSuperseded``, then salvaging the structures the dead
        drain never will."""
        eng = self._engine
        with eng._submit_lock:
            eng._running = False
            eng._draining = True
            eng._restart_pending = True
        eng._work.set()
        if eng._watchdog is not None:
            eng._watchdog.stop()
        old = eng._sched
        if old is not None:
            old.join(timeout=self.join_timeout_s)
            if old.is_alive():
                if self._logger is not None:
                    self._logger.errorf(
                        "supervisor: scheduler thread wedged past %.1fs "
                        "join; abandoning it (epoch fence)",
                        self.join_timeout_s,
                    )
                with eng._submit_lock:
                    eng._epoch += 1
                self._salvage_abandoned()
            eng._sched = None

    def _salvage_abandoned(self) -> None:
        """The wedged thread will never run its drain: collect every
        live request from the engine structures ourselves — retryable
        ones park for replay, the rest get their terminal error now."""
        eng = self._engine
        reqs: list[_GenRequest] = []
        with eng._submit_lock:
            while True:
                try:
                    reqs.append(eng._pending.get_nowait())
                except queue.Empty:
                    break
            for seq in eng._slots:
                if seq is not None:
                    reqs.append(seq.request)
            for st in eng._prefilling.values():
                reqs.append(st.request)
            reqs.extend(eng._wait_kv)
            eng._wait_kv.clear()
            eng._queued_tokens = 0
            eng._tenant_queued.clear()
            if eng._tenant_ledger is not None:
                # Live queue shares reset with the queues (replays
                # re-note on requeue); cumulative attribution survives
                # the restart like the flight recorder does.
                eng._tenant_ledger.reset_queued()
            # Partition ONCE: retryability can flip between evaluations
            # (a cancel racing in), and a request must land on exactly
            # one side.
            retry: list[_GenRequest] = []
            drop: list[_GenRequest] = []
            for req in reqs:
                (retry if req.retryable() else drop).append(req)
            eng._replay.extend(retry)
        for req in drop:
            self._fail_request(req)

    def _requeue_salvaged(self) -> tuple[int, int]:
        """Requeue every salvaged request on the restarted engine;
        returns (replayed, dropped). Drops — cancelled or expired during
        the outage, or a full fresh queue — fail through the existing
        terminal error path so streams end with an explicit error event,
        never a silent truncation."""
        eng = self._engine
        with eng._submit_lock:
            reqs, eng._replay = list(eng._replay), []
        replayed = dropped = 0
        for req in reqs:
            if eng.requeue_replay(req):
                replayed += 1
                continue
            if (
                req.retryable()
                and not eng._running
                and not self._stopping  # graftlint: disable=GL020 — monotonic latch probe; a stale False parks the request for a recovery stop() then fails itself
            ):
                # Still retryable, but the fresh engine already died
                # again (tight crash loop): park it back — the NEXT
                # recovery replays it, or _give_up fails it with the
                # crash-loop terminal error. (During shutdown there is
                # no next recovery: fall through to the terminal error.)
                with eng._submit_lock:
                    eng._replay.append(req)
                continue
            # A request the fresh queue could not take (full) may still
            # continue on a sibling replica before failing terminally.
            if eng.try_handoff(req):
                continue
            dropped += 1
            self._fail_request(req)
        return replayed, dropped

    def _fail_request(
        self, req: _GenRequest, exc: Optional[BaseException] = None
    ) -> None:
        """Terminal error + stream sentinel. The cancelled/deadline
        classification routes through ``scheduler._reap_reason`` — the
        ONE retirement predicate — so a retirement reason added there
        surfaces identically for requests failed across a restart."""
        from gofr_tpu.errors import (
            ErrorDeadlineExceeded,
            ErrorRequestCancelled,
            ErrorServiceUnavailable,
        )

        if exc is None:
            reason = self._engine._reap_reason(req)
            if reason == "cancelled":
                exc = ErrorRequestCancelled()
            elif reason == "deadline":
                exc = ErrorDeadlineExceeded(
                    f"after {len(req.token_ids)} generated token(s)"
                )
            else:
                exc = ErrorServiceUnavailable(
                    "engine restart could not carry this request; retry"
                )
        from concurrent.futures import InvalidStateError

        try:
            if not req.future.done():
                req.future.set_exception(exc)
        except InvalidStateError:  # caller cancelled concurrently
            pass
        req.stream.put(None)
        # Observability: a request failed across a restart still gets
        # exactly one flight-recorder entry/trace (latched — no double
        # summarization when this races a scheduler terminal path), and
        # the tenant ledger attributes it at the same seam (its own
        # latch) so attribution stays total across restarts too.
        if req.timeline is not None:
            req.timeline.finish(
                "error", type(exc).__name__,
                output_tokens=len(req.token_ids),
            )
        if self._engine._tenant_ledger is not None:
            self._engine._tenant_ledger.finish_request(req, "error")

    def _give_up(self, reason: str) -> None:
        """Crash loop: ``max_restarts`` consecutive failures — land in
        DOWN (health reports it, orchestrators reroute) and fail every
        live request instead of restarting forever. Runs a full
        teardown first: when the budget is exhausted by a watchdog trip
        the wedged scheduler never drained, so requests still sit in
        the queue/slots/prefill structures — _teardown salvages them
        into the replay list, and everything parked there fails with
        the explicit crash-loop error (no caller may hang on DOWN)."""
        eng = self._engine
        if self._logger is not None:
            self._logger.errorf(
                "supervisor: %d consecutive restart failures (%s); "
                "engine is DOWN until an operator intervenes",
                self._consecutive, reason,
            )
        self._teardown()
        eng._set_state("DOWN")
        from gofr_tpu.errors import ErrorServiceUnavailable

        exc = ErrorServiceUnavailable(
            f"engine DOWN after {self._consecutive} restart attempts "
            f"({reason}); retry against another replica"
        )
        with eng._submit_lock:
            reqs, eng._replay = list(eng._replay), []
        for req in reqs:
            # Replica-tier failover: a still-retryable request this
            # replica can no longer serve continues on a SIBLING replica
            # when a pool handoff is installed — the client's stream and
            # future carry over; only unplaceable requests get the
            # crash-loop terminal error.
            if eng.try_handoff(req):
                continue
            self._fail_request(req, exc)
