"""Request-lifecycle observability: tracing, phase metrics, flight recorder.

Five layers of machinery now sit between a socket and a token
(admission → queue → chunked prefill → radix-cache alias → decode →
emit, with supervisor replay and replica failover underneath), and until
this module the only latency number a request ever exported was a single
``ttft_s`` field. This is the layer every later perf PR is measured
through; it owns three things:

* **Tracing** — every generation carries a :class:`RequestTimeline`
  whose trace id is adopted from the incoming W3C ``traceparent``
  (HTTP header / gRPC metadata) or minted at submit. Child spans for
  queue-wait, admission (with shed outcome), each prefill chunk,
  emit-flush, and decode — plus instant spans for supervisor replays
  and replica-pool failover/hedge hops — are emitted **once, at
  retirement**, from the timeline's already-collected host timestamps,
  so tracing adds zero work to the scheduler's dispatch path and the
  spans stitch into one trace across replicas (``HTTPReplica``
  propagates ``traceparent`` downstream).
* **Phase metrics** — histograms ``app_tpu_queue_wait_seconds``,
  ``app_tpu_prefill_seconds``, ``app_tpu_ttft_seconds``,
  ``app_tpu_inter_token_seconds``, ``app_tpu_e2e_seconds``: exactly ONE
  ``record`` per request per phase, computed at retirement from
  host-side timestamps already in hand. Never per token, never a new
  host↔device pull (graftlint GL006/GL010/GL011 stay clean).
* **Flight recorder** — a fixed-size ring of per-request timelines
  (phase durations, token counts, prefix-cache hit tokens,
  shed/cancel/replay/failover annotations, trace id) served at
  ``/debug/flight`` on the ops port. Slow and errored requests are
  **pinned** into a separate bounded ring so a burst of healthy traffic
  cannot evict the interesting ones.

Overhead contract: with the layer off (``TPU_FLIGHT_RECORDER=0``, no
metrics manager, no active trace exporter) ``begin`` returns ``None``
and every scheduler hook is a single ``is not None`` check. With it on,
the per-request cost is one small object, a handful of monotonic clock
reads at *window* granularity, and one deferred summarization at
retirement — measured <2% tok/s on the CPU-fallback bench A/B.

Determinism: the clock is injectable (this package's standing contract —
tests state time instead of sleeping) and the flight recorder assigns
monotonic request ids, so eviction/pinning tests are exact.
"""

from __future__ import annotations


import time
from collections import deque
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck
from gofr_tpu.tracing import get_tracer
from gofr_tpu.tracing.tracer import Tracer, _rand_hex, current_span


def parse_traceparent(tp: str) -> tuple[Optional[str], Optional[str]]:
    """W3C ``traceparent`` string → (trace_id, span_id), (None, None)
    when malformed — same validation as ``tracing.extract_traceparent``
    but for a bare value instead of a header dict."""
    parts = (tp or "").split("-")
    if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
        return parts[1], parts[2]
    return None, None


def tracer_active(tracer: Optional[Tracer] = None) -> bool:
    """True when completed spans actually go somewhere (an exporter that
    is not the no-op) — span construction is skipped entirely
    otherwise."""
    t = tracer or get_tracer()
    exporter = getattr(t, "_exporter", None)
    return exporter is not None and not getattr(exporter, "is_noop", False)


def emit_instant_span(
    name: str,
    traceparent: Optional[str],
    attributes: Optional[dict[str, Any]] = None,
) -> None:
    """Emit a zero-duration span (a trace *annotation*: hedge hops and
    similar events that are not tied to a request timeline). No-op
    without an active exporter or a parseable ``traceparent``."""
    tracer = get_tracer()
    if not tracer_active(tracer):
        return
    trace_id, parent_id = (
        parse_traceparent(traceparent) if traceparent else (None, None)
    )
    if trace_id is None:
        span = current_span()
        if span is None:
            return
        trace_id, parent_id = span.trace_id, span.span_id
    now_ns = time.time_ns()
    tracer.emit_span(
        name,
        trace_id=trace_id,
        parent_span_id=parent_id,
        start_ns=now_ns,
        end_ns=now_ns,
        attributes=attributes,
    )


def emit_boot_span(
    name: str,
    start_ns: int,
    end_ns: int,
    attributes: Optional[dict[str, Any]] = None,
) -> None:
    """Emit a completed boot-phase span (``tpu.shard_init`` and kin):
    engine construction has no request to ride, so the span joins the
    ambient trace when one is active (an app booting under a traced
    startup hook) and otherwise mints its own trace id — an operator
    asking "why did boot take 40s" still finds the mesh-build/param-
    sharding window. No-op without an active exporter."""
    tracer = get_tracer()
    if not tracer_active(tracer):
        return
    span = current_span()
    trace_id = span.trace_id if span is not None else _rand_hex(16)
    parent_id = span.span_id if span is not None else None
    tracer.emit_span(
        name,
        trace_id=trace_id,
        parent_span_id=parent_id,
        start_ns=start_ns,
        end_ns=end_ns,
        attributes=attributes,
    )


class RequestTimeline:
    """One request's host-side lifecycle record.

    Written by the scheduler thread at window granularity (every method
    takes the timestamp as an argument — the caller reads the clock once
    per window/chunk, never per row; graftlint GL011). Annotations
    (replay, failover) may arrive from supervisor/pool threads;
    ``finish`` is latched under a lock so exactly one summarization
    happens no matter which terminal path wins a race.
    """

    __slots__ = (
        "hub", "rid", "trace_id", "parent_span_id", "enqueued",
        "wall_ns_base", "mono_base", "admitted", "admissions",
        "prefill_done", "first_token", "done", "outcome", "finish_reason",
        "chunks", "annotations", "transfers", "prompt_tokens",
        "output_tokens", "prefix_hit_tokens", "replays", "tenant",
        "_lock", "_finished",
    )

    def __init__(
        self,
        hub: "RequestObservability",
        rid: int,
        trace_id: str,
        parent_span_id: Optional[str],
        enqueued: float,
        wall_ns_base: int,
        prompt_tokens: int,
        tenant: str = "",
    ) -> None:
        self.hub = hub
        self.rid = rid
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.enqueued = enqueued
        # Wall↔monotonic anchor pair: phases are measured monotonic (NTP
        # steps must not skew durations), spans need wall-clock ns.
        self.wall_ns_base = wall_ns_base
        self.mono_base = enqueued
        self.admitted: Optional[float] = None
        self.admissions = 0
        self.prefill_done: Optional[float] = None
        self.first_token: Optional[float] = None
        self.done: Optional[float] = None
        self.outcome = ""
        self.finish_reason = ""
        # (start, end, tokens) per dispatched prefill chunk step.
        self.chunks: list[tuple[float, float, int]] = []
        # (name, t, attrs) — shed/replay/failover events.
        self.annotations: list[tuple[str, float, dict[str, Any]]] = []
        # (source, target, start, end, result) — disaggregated-tier KV
        # transfers between the prefill and decode phases; rendered as
        # a `tpu.transfer` span with real duration, unlike the instant
        # annotations above.
        self.transfers: list[tuple[str, str, float, float, str, str]] = []
        self.prompt_tokens = prompt_tokens
        self.output_tokens = 0
        self.prefix_hit_tokens = 0
        self.replays = 0
        # The submitting tenant (X-Tenant-Id), carried so finalize can
        # feed per-tenant SLO overrides (serving/slo.py) without a
        # second measurement path.
        self.tenant = tenant
        self._lock = lockcheck.make_lock("RequestTimeline._lock")
        self._finished = False

    # -- scheduler-thread marks (timestamps passed in; see class doc) --

    def mark_admitted(self, now: float) -> None:
        if self.admitted is None:
            self.admitted = now
        self.admissions += 1

    def note_prefix_hit(self, tokens: int) -> None:
        self.prefix_hit_tokens += tokens

    def note_chunk(self, start: float, end: float, tokens: int) -> None:
        self.chunks.append((start, end, tokens))

    def mark_prefill_done(self, now: float) -> None:
        if self.prefill_done is None:
            self.prefill_done = now

    def mark_first_token(self, now: float) -> None:
        if self.first_token is None:
            self.first_token = now

    # -- cross-thread annotations --------------------------------------

    def annotate(
        self, name: str, now: float, **attrs: Any
    ) -> None:
        self.annotations.append((name, now, attrs))

    def note_replay(self, mode: str, now: float) -> None:
        self.replays += 1
        self.annotate("tpu.replay", now, mode=mode)

    def note_failover(self, src: str, dst: str, now: float) -> None:
        self.annotate("tpu.failover", now, source=src, target=dst)

    def note_transfer(
        self,
        src: str,
        dst: str,
        start: float,
        end: float,
        result: str,
        leg: str = "host",
    ) -> None:
        """One disaggregated-tier KV transfer hop (prefill replica →
        decode replica), recorded from the pool's transfer thread —
        shows up in /debug/flight and as a `tpu.transfer` child span
        between the prefill and decode phases of the request's ONE
        trace. ``leg`` names the rung that carried the blocks (dma /
        device / wire / host; "none" for hops that shipped nothing,
        e.g. a failover fallback). Remote prefill-SOURCE pulls record
        here too — result ``source_hit`` / ``source_miss`` /
        ``source_rejected`` / ``source_error`` with ``leg`` naming the
        pull rung (dma / wire) — so the whole pull descent shows on the
        same trace as the request it warmed."""
        self.transfers.append((src, dst, start, end, result, leg))

    def traceparent(self) -> str:
        """The W3C header a downstream hop (wire-leg tier transfer,
        remote adoption) forwards so its spans join THIS request's
        trace. The span-id field names the caller's parent span when
        one was adopted, else a fresh id — trace-id continuity is the
        contract; the parent link is best-effort, exactly like any
        cross-host hop."""
        return (
            f"00-{self.trace_id}-"
            f"{self.parent_span_id or _rand_hex(8)}-01"
        )

    # -- terminal ------------------------------------------------------

    def finish(
        self,
        outcome: str,
        finish_reason: str = "",
        output_tokens: Optional[int] = None,
    ) -> None:
        """Latched terminal summarization: histograms (one record per
        phase), deferred span emission, flight-recorder entry. Safe to
        call from any terminal path — retire, lifecycle reap, drain,
        supervisor fail — exactly the first call wins."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
        self.done = self.hub.now()
        self.outcome = outcome
        self.finish_reason = finish_reason
        if output_tokens is not None:
            self.output_tokens = output_tokens
        self.hub.finalize(self)

    @property
    def finished(self) -> bool:
        return self._finished

    # -- rendering -----------------------------------------------------

    def wall_ns(self, t: float) -> int:
        return self.wall_ns_base + int((t - self.mono_base) * 1e9)

    def phases(self) -> dict[str, float]:
        """Durations (seconds) of the completed phases; a phase the
        request never reached is simply absent."""
        out: dict[str, float] = {}
        if self.admitted is not None:
            out["queue_wait_s"] = self.admitted - self.enqueued
        if self.prefill_done is not None and self.admitted is not None:
            out["prefill_s"] = self.prefill_done - self.admitted
        if self.first_token is not None:
            out["ttft_s"] = self.first_token - self.enqueued
        if self.done is not None and self.first_token is not None:
            decode_s = self.done - self.first_token
            out["decode_s"] = decode_s
            if self.output_tokens >= 2:
                out["inter_token_s"] = decode_s / (self.output_tokens - 1)
        if self.done is not None:
            out["e2e_s"] = self.done - self.enqueued
        return out

    def to_dict(self) -> dict[str, Any]:
        """The flight-recorder / ``/debug/flight`` entry."""
        return {
            "rid": self.rid,
            "trace_id": self.trace_id,
            "outcome": self.outcome,
            "finish_reason": self.finish_reason,
            "enqueued_unix": self.wall_ns_base / 1e9,
            "phases": {
                k: round(v, 6) for k, v in self.phases().items()
            },
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefill_chunks": len(self.chunks),
            "replays": self.replays,
            "transfers": [
                {
                    "source": src,
                    "target": dst,
                    "duration_s": round(end - start, 6),
                    "result": result,
                    "leg": leg,
                }
                for src, dst, start, end, result, leg in self.transfers
            ],
            "annotations": [
                {
                    "name": name,
                    "t_offset_s": round(t - self.enqueued, 6),
                    **{k: str(v) for k, v in attrs.items()},
                }
                for name, t, attrs in self.annotations
            ],
        }


class FlightRecorder:
    """Fixed-size ring of retired request timelines, with slow/errored
    ones pinned into their own bounded ring so a burst of healthy
    traffic cannot evict the requests worth looking at."""

    def __init__(
        self,
        capacity: int = 256,
        pin_capacity: int = 64,
        slow_s: float = 5.0,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self.pin_capacity = max(1, int(pin_capacity))
        self.slow_s = float(slow_s)
        self._lock = lockcheck.make_lock("FlightRecorder._lock")
        self._ring: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._pinned: deque[dict[str, Any]] = deque(
            maxlen=self.pin_capacity
        )

    def record(self, entry: dict[str, Any], pin: bool) -> None:
        with self._lock:
            if pin:
                self._pinned.append(entry)
            else:
                self._ring.append(entry)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "pin_capacity": self.pin_capacity,
                "slow_s": self.slow_s,
                "records": list(self._ring),
                "pinned": list(self._pinned),
            }


#: Histogram names, registered in ``container.register_framework_metrics``.
PHASE_HISTOGRAMS = {
    "queue_wait_s": "app_tpu_queue_wait_seconds",
    "prefill_s": "app_tpu_prefill_seconds",
    "ttft_s": "app_tpu_ttft_seconds",
    "inter_token_s": "app_tpu_inter_token_seconds",
    "e2e_s": "app_tpu_e2e_seconds",
}


class RequestObservability:
    """Per-engine observability hub: mints timelines at submit, owns the
    flight recorder, and turns finished timelines into histogram records
    and spans. A timeline keeps a reference to the hub that minted it,
    so a request adopted by a sibling replica (failover) still lands in
    its origin's recorder exactly once."""

    def __init__(
        self,
        model_name: str,
        *,
        metrics: Any = None,
        recorder: Optional[FlightRecorder] = None,
        clock: Callable[[], float] = time.monotonic,
        wall_ns: Callable[[], int] = time.time_ns,
    ) -> None:
        self.model_name = model_name
        self._metrics = metrics
        self.recorder = recorder
        self._clock = clock
        self._wall_ns = wall_ns
        self._seq_lock = lockcheck.make_lock("RequestObservability._seq_lock")
        self._seq = 0
        # SLO evaluation (serving/slo.py): when the engine configures
        # objectives, finalize feeds every retired timeline's outcome
        # and phases into the burn-rate engine — the PR 6 phase records
        # ARE the SLO input, no second measurement path.
        self.slo: Any = None

    def now(self) -> float:
        return self._clock()

    def begin(
        self,
        prompt_tokens: int,
        traceparent: Optional[str] = None,
        tenant: str = "",
    ) -> Optional[RequestTimeline]:
        """Mint a timeline for a submitting request, adopting the trace
        context from ``traceparent``, then from the calling task's
        current span, then minting a fresh trace id. Returns None when
        the whole layer is off (no recorder, no metrics, no active
        exporter) so the scheduler hooks cost one ``is not None``."""
        if (
            self.recorder is None
            and self._metrics is None
            and self.slo is None
            and not tracer_active()
        ):
            return None
        trace_id: Optional[str] = None
        parent_id: Optional[str] = None
        if traceparent:
            trace_id, parent_id = parse_traceparent(traceparent)
        if trace_id is None:
            span = current_span()
            if span is not None:
                trace_id, parent_id = span.trace_id, span.span_id
        if trace_id is None:
            trace_id = _rand_hex(16)
        with self._seq_lock:
            self._seq += 1
            rid = self._seq
        return RequestTimeline(
            self, rid, trace_id, parent_id,
            enqueued=self._clock(),
            wall_ns_base=self._wall_ns(),
            prompt_tokens=prompt_tokens,
            tenant=tenant,
        )

    def note_shed(
        self, timeline: Optional[RequestTimeline], reason: str
    ) -> None:
        """Admission rejected the request (429/503/504 before a slot):
        close its timeline with the shed outcome — the recorder pins it,
        and the trace shows an admission span with the outcome."""
        if timeline is None:
            return
        timeline.annotate("tpu.shed", self.now(), reason=reason)
        timeline.finish("shed", finish_reason=reason)

    # -- terminal summarization ---------------------------------------

    def finalize(self, timeline: RequestTimeline) -> None:
        """Called exactly once per timeline (from ``finish``): histogram
        records, deferred span emission, flight-recorder entry."""
        phases = timeline.phases()
        if self._metrics is not None:
            for key, metric in PHASE_HISTOGRAMS.items():
                if key in phases:
                    self._metrics.record_histogram(
                        metric, phases[key], "model", self.model_name
                    )
        if self.slo is not None:
            # Burn-rate input (serving/slo.py): the retired request's
            # outcome + phases, judged at request granularity — with
            # the tenant so per-tenant overrides see it too.
            self.slo.observe(
                timeline.outcome, phases, tenant=timeline.tenant
            )
        tracer = get_tracer()
        if tracer_active(tracer):
            self._emit_spans(tracer, timeline, phases)
        if self.recorder is not None:
            e2e = phases.get("e2e_s", 0.0)
            pin = (
                timeline.outcome not in ("ok",)
                or e2e > self.recorder.slow_s
            )
            self.recorder.record(timeline.to_dict(), pin)

    def _emit_spans(
        self,
        tracer: Tracer,
        tl: RequestTimeline,
        phases: dict[str, float],
    ) -> None:
        """One ``tpu.request`` span (child of the transport span when a
        traceparent came in) with phase children — all from timestamps
        already collected, nothing touched the dispatch path."""
        done = tl.done if tl.done is not None else tl.enqueued
        root = tracer.emit_span(
            "tpu.request",
            trace_id=tl.trace_id,
            parent_span_id=tl.parent_span_id,
            start_ns=tl.wall_ns(tl.enqueued),
            end_ns=tl.wall_ns(done),
            attributes={
                "tpu.model": self.model_name,
                "tpu.outcome": tl.outcome,
                "tpu.prompt_tokens": tl.prompt_tokens,
                "tpu.output_tokens": tl.output_tokens,
                "tpu.replays": tl.replays,
            },
            status="OK" if tl.outcome == "ok" else "ERROR",
        )
        pid = root.span_id

        def child(
            name: str, start: float, end: float, **attrs: Any
        ) -> None:
            tracer.emit_span(
                name,
                trace_id=tl.trace_id,
                parent_span_id=pid,
                start_ns=tl.wall_ns(start),
                end_ns=tl.wall_ns(end),
                attributes=attrs,
            )

        if tl.admitted is not None:
            child("tpu.queue_wait", tl.enqueued, tl.admitted)
            child(
                "tpu.admission", tl.admitted, tl.admitted,
                outcome="admitted",
                prefix_hit_tokens=tl.prefix_hit_tokens,
            )
        for i, (start, end, tokens) in enumerate(tl.chunks):
            child(
                "tpu.prefill.chunk", start, end,
                index=i, tokens=tokens,
            )
        if tl.prefill_done is not None and tl.first_token is not None:
            child("tpu.emit_flush", tl.prefill_done, tl.first_token)
        for src, dst, start, end, result, leg in tl.transfers:
            # The disaggregated-tier hop: a real-duration span between
            # the prefill phase (on `src`) and the decode phase (on
            # `dst`), in the SAME trace, tagged with the leg that
            # carried the blocks (device / wire / host).
            child(
                "tpu.transfer", start, end,
                source=src, target=dst, result=result, leg=leg,
            )
        if tl.first_token is not None:
            child(
                "tpu.decode", tl.first_token, done,
                tokens=tl.output_tokens,
            )
        for name, t, attrs in tl.annotations:
            child(name, t, t, **attrs)
