"""Closed-loop overload control: the brownout ladder (ISSUE 13).

PR 11 made the pod *measure* its promises — ``app_tpu_slo_burn_rate``
and ``app_tpu_slo_compliant`` — but nothing *acted* on them: under a
sustained overload storm the fleet served every admitted request at
full quality until the static admission budgets tripped, so tail
latency collapsed for everyone before anyone was degraded. This module
is the runtime twin of the multi-window burn-rate alert: it sheds
**quality** in graded steps before shedding **requests**, and sheds the
right requests first.

A :class:`BrownoutController` maps the :class:`~gofr_tpu.serving.slo.
SLOEngine`'s fast-window (5m) burn rate — plus, optionally, the PR 10
HBM headroom signal — onto a small ladder of degradation levels:

* **L0** — nominal. Every action below is byte-identically off.
* **L1** — shed *optional* work: the replica pool suppresses latency
  hedges against this replica and skips an in-proc replica's
  token-generating synthetic probes on alternating sweeps (half the
  probe load, but restart-on-evidence still fires within two sweeps;
  remote replicas always probe — their probe is a cheap health fetch
  and the only path that refreshes the cached advertisement), and new
  admits
  have ``max_new_tokens`` clamped to ``TPU_BROWNOUT_MAX_NEW``. The
  clamp is *advertised*: the response carries
  ``finish_reason="length"`` plus a ``brownout`` field so clients see
  the truncation was deliberate, not a bug.
* **L2** — AIMD on the effective admission budget: a multiplicative
  cut (``TPU_BROWNOUT_AIMD_CUT``) of the ``TPU_QUEUE_TOKENS`` /
  ``TPU_QUEUE_MAX`` budget on entry, additive recovery
  (``TPU_BROWNOUT_RECOVER_PER_S`` of the budget per second) while the
  signal is below the enter threshold. Shedding is **priority-aware**:
  requests carry an SLO class (``X-SLO-Class`` header / ``x-slo-class``
  gRPC metadata: ``interactive`` | ``standard`` | ``batch``, default
  ``standard``, per-tenant default via ``TPU_TENANT_SLO_CLASS``) and
  each class may only fill a fraction of the cut budget
  (:data:`CLASS_ADMIT_FRACTION`) — batch is consumed first,
  interactive last. Every 429 is stamped ``reason=brownout`` with a
  ``Retry-After`` derived from the controller's projected recovery.
* **L3** — the replica marks itself non-compliant:
  ``ReplicaPool.pick()`` deprioritizes it exactly like the tier-role
  preference (never a partition — an all-L3 pool still serves), and
  ``PoolScaler`` treats sustained L2+ as scale-up pressure.

Discipline:

* **Hysteresis everywhere** (graftlint GL017 is the static twin): a
  level is entered only after the 5m burn holds at or above
  ``TPU_BROWNOUT_ENTER`` for ``TPU_BROWNOUT_SUSTAIN_S`` — one bad tick
  never flips a level — and exited only after it holds at or below
  ``TPU_BROWNOUT_EXIT`` for ``TPU_BROWNOUT_EXIT_SUSTAIN_S``. Between
  the thresholds the ladder holds.
* **Window granularity** (GL011): the scheduler evaluates the
  controller once per loop pass with one clock read; nothing here is
  per-token or per-request.
* **Determinism**: the clock is injectable; tests state time instead
  of sleeping.
* **Off is off**: ``TPU_BROWNOUT=0`` builds no controller — every hook
  is one ``is not None`` — and at L0 an armed controller changes no
  behavior (the AIMD factor snaps back to exactly 1.0 on reaching L0).
"""

from __future__ import annotations


import math
import time
from typing import Any, Callable, Mapping, Optional

from gofr_tpu.analysis import lockcheck

#: The SLO-class vocabulary (bounded: it appears in metric labels).
SLO_CLASSES = ("interactive", "standard", "batch")

#: Fraction of the (already AIMD-cut) admission budget each class may
#: fill at L2+. Batch fills its smaller allowance first and sheds
#: first; interactive keeps the whole cut budget and sheds last.
CLASS_ADMIT_FRACTION: Mapping[str, float] = {
    "batch": 0.5,
    "standard": 0.8,
    "interactive": 1.0,
}

#: Highest ladder rung.
MAX_LEVEL = 3


def normalize_slo_class(value: str) -> str:
    """Clamp a request-controlled class string to the bounded
    vocabulary ("" when it names no known class — the caller falls back
    to the tenant default, then ``standard``)."""
    v = str(value or "").strip().lower()
    return v if v in SLO_CLASSES else ""


def parse_tenant_class_map(spec: str) -> dict[str, str]:
    """``TPU_TENANT_SLO_CLASS="acme=batch,ops=interactive"`` → per-
    tenant default SLO class. Unknown class names are dropped (the
    request falls back to ``standard``) rather than failing boot.
    Tenant keys are lower-cased: the lookup matches ``X-Tenant-Id``
    case-insensitively, the same contract as the
    ``TPU_SLO_TENANT_<NAME>_*`` per-tenant SLO overrides (whose env-key
    segment is conventionally upper-case)."""
    out: dict[str, str] = {}
    for entry in str(spec or "").replace(";", ",").split(","):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        tenant, cls = entry.split("=", 1)
        cls = normalize_slo_class(cls)
        if tenant.strip() and cls:
            out[tenant.strip().lower()] = cls
    return out


class BrownoutController:
    """Burn-rate-driven degradation ladder (see the module docstring).

    One instance per engine. ``evaluate`` runs on the scheduler thread
    once per loop pass; the action reads (``level``, ``clamp_max_new``,
    ``admission_fraction``, ``routable``) run on submit/probe threads —
    all state is mutated under one lock and the hot reads are single
    attribute loads."""

    def __init__(
        self,
        model_name: str,
        *,
        enter_burn: float = 2.0,
        exit_burn: float = 1.0,
        sustain_s: float = 10.0,
        exit_sustain_s: float = 30.0,
        max_new_tokens: int = 256,
        aimd_cut: float = 0.5,
        recover_per_s: float = 0.02,
        min_headroom: float = 0.0,
        metrics: Any = None,
        logger: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.model_name = model_name
        # Hysteresis pair: exit must sit at or below enter or the
        # ladder would oscillate inside the dead band it is meant to
        # create.
        self.enter_burn = max(0.0, float(enter_burn))
        self.exit_burn = min(self.enter_burn, max(0.0, float(exit_burn)))
        self.sustain_s = max(0.0, float(sustain_s))
        self.exit_sustain_s = max(0.0, float(exit_sustain_s))
        self.max_new_tokens = max(0, int(max_new_tokens))
        self.aimd_cut = min(1.0, max(0.05, float(aimd_cut)))
        self.recover_per_s = max(1e-4, float(recover_per_s))
        self.min_headroom = max(0.0, float(min_headroom))
        self._metrics = metrics
        self._logger = logger
        self._clock = clock
        self._lock = lockcheck.make_lock("BrownoutController._lock")
        self.level = 0
        #: AIMD multiplier on the admission budget: 1.0 nominal, cut
        #: multiplicatively on each climb into L2+, recovered
        #: additively, snapped to exactly 1.0 at L0 (byte-identity).
        self.budget_factor = 1.0
        # Sustain anchors (GL017 discipline): the first evaluation that
        # saw the signal continuously over (resp. under) its threshold.
        self._over_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._last_eval: Optional[float] = None
        # Last inputs, for /debug/brownout.
        self._last_burn = 0.0
        self._last_headroom: Optional[float] = None
        self._transitions = {"up": 0, "down": 0}
        self._actions: dict[str, int] = {}
        self._publish_level()

    # -- control loop (scheduler thread, once per window) ----------------

    def evaluate(
        self,
        burn_5m: float,
        headroom: Optional[float] = None,
        now: Optional[float] = None,
    ) -> int:
        """One control decision from the 5m burn rate (and, when the
        headroom floor is armed, the HBM headroom ratio). Returns the
        level after the decision. Climbs one rung per sustained-over
        period, descends one rung per sustained-clear period — exit is
        confirmed on the 5m window actually recovering, never on mere
        time passing at a lower level."""
        t = self._clock() if now is None else now
        with self._lock:
            dt = (
                max(0.0, t - self._last_eval)
                if self._last_eval is not None else 0.0
            )
            self._last_eval = t
            self._last_burn = float(burn_5m)
            self._last_headroom = headroom
            headroom_pressure = (
                self.min_headroom > 0.0
                and headroom is not None
                and math.isfinite(headroom)
                and headroom < self.min_headroom
            )
            over = burn_5m >= self.enter_burn or headroom_pressure
            clear = burn_5m <= self.exit_burn and not headroom_pressure
            # Additive recovery while the signal is not over: the
            # budget creeps back toward nominal even before the ladder
            # steps down (slow-start after the cut). At ANY level above
            # 0 — a factor frozen at L1 would keep inflating every
            # Retry-After's recovery floor and compound the next L2
            # entry's cut. (At L0 the factor is already snapped to 1.)
            if not over and self.budget_factor < 1.0:
                self.budget_factor = min(
                    1.0, self.budget_factor + self.recover_per_s * dt
                )
            if over:
                self._clear_since = None
                if self._over_since is None:
                    self._over_since = t
                elif (
                    t - self._over_since >= self.sustain_s
                    and self.level < MAX_LEVEL
                ):
                    self._step(+1, t)
                    self._over_since = t  # re-arm for the next rung
            elif clear:
                self._over_since = None
                if self._clear_since is None:
                    self._clear_since = t
                elif (
                    t - self._clear_since >= self.exit_sustain_s
                    and self.level > 0
                ):
                    self._step(-1, t)
                    self._clear_since = t  # one rung per clear period
            else:
                # Inside the hysteresis band: hold the level, reset
                # both anchors — neither climb nor descent may count
                # band time toward its sustain window.
                self._over_since = None
                self._clear_since = None
            return self.level

    def _step(self, direction: int, now: float) -> None:
        """One ladder transition (call under the lock)."""
        prev = self.level
        self.level = min(MAX_LEVEL, max(0, self.level + direction))
        if self.level == prev:
            return
        if direction > 0 and self.level >= 2:
            # Multiplicative cut on entering (or climbing within) the
            # admission-shedding rungs.
            self.budget_factor = max(0.01, self.budget_factor * self.aimd_cut)
        if self.level == 0:
            # Byte-identity contract: at L0 every action is exactly
            # off, so the budget snaps back to nominal.
            self.budget_factor = 1.0
        key = "up" if direction > 0 else "down"
        self._transitions[key] += 1
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_brownout_transitions_total",
                "model", self.model_name, "direction", key,
            )
        self._publish_level()
        if self._logger is not None:
            self._logger.warnf(
                "brownout level %d -> %d (burn_5m=%.2f, headroom=%s, "
                "budget_factor=%.3f)", prev, self.level, self._last_burn,
                "n/a" if self._last_headroom is None
                else f"{self._last_headroom:.3f}",
                self.budget_factor,
            )

    def _publish_level(self) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_brownout_level", float(self.level),
                "model", self.model_name,
            )

    def force_level(self, level: int, now: Optional[float] = None) -> None:
        """Jump the ladder to ``level`` (ops drills and deterministic
        tests; the next ``evaluate`` resumes normal hysteresis from
        here). Out-of-range targets clamp — ``_step`` clamps too, so an
        unclamped loop target could never be reached and would spin
        forever holding the lock."""
        level = min(MAX_LEVEL, max(0, int(level)))
        t = self._clock() if now is None else now
        with self._lock:
            while self.level < level:
                self._step(+1, t)
            while self.level > level:
                self._step(-1, t)
            self._over_since = None
            self._clear_since = None

    # -- action surface ---------------------------------------------------

    @property
    def shedding(self) -> bool:
        """L2+ — the admission budget is cut. (Pool-side actions —
        hedge suppression, probe skipping, scaler pressure — work on
        the ADVERTISED level instead: remote replicas only ship an
        integer over the health wire, so the pool compares levels, not
        controller predicates.)"""
        return self.level >= 2

    def routable(self) -> bool:
        """False at L3: the replica advertises non-compliance so the
        pool deprioritizes it exactly like the SLO burn signal."""
        return self.level < MAX_LEVEL

    def clamp_max_new(self, requested: int) -> int:
        """L1+ clamp on a new admit's generation budget (0 = no clamp
        configured)."""
        if self.level >= 1 and self.max_new_tokens > 0:
            return min(int(requested), self.max_new_tokens)
        return int(requested)

    def admission_fraction(self, slo_class: str) -> float:
        """The fraction of the nominal admission budget ``slo_class``
        may fill right now: 1.0 below L2 (byte-identical admission),
        else the AIMD factor scaled by the class allowance — batch
        first into the cut, interactive last."""
        if self.level < 2:
            return 1.0
        frac = CLASS_ADMIT_FRACTION.get(slo_class, CLASS_ADMIT_FRACTION["standard"])
        return self.budget_factor * frac

    def projected_recovery_s(self, now: Optional[float] = None) -> float:
        """Deterministic Retry-After basis for brownout sheds: the time
        for the ladder to descend to L1 (one exit-sustain period per
        rung above it, less any clear time already banked) plus the
        AIMD budget's additive recovery to nominal. Always positive —
        a 429 must never tell the client "retry immediately" while the
        controller is still degraded."""
        t = self._clock() if now is None else now
        with self._lock:
            rungs = max(0, self.level - 1)
            wait = rungs * self.exit_sustain_s
            if self._clear_since is not None and rungs > 0:
                wait -= min(
                    self.exit_sustain_s, max(0.0, t - self._clear_since)
                )
            wait += (1.0 - self.budget_factor) / self.recover_per_s
            return max(1.0, wait)

    def note_action(self, action: str) -> None:
        """Count one ladder action (``clamp_tokens``, ``suppress_hedge``,
        ``skip_probe``, ``shed_<class>``) — the per-action counters the
        storm suite and the bench A/B read."""
        with self._lock:
            self._actions[action] = self._actions.get(action, 0) + 1
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_brownout_actions_total",
                "model", self.model_name, "action", action,
            )

    def shed_count(self, slo_class: str) -> int:
        with self._lock:
            return self._actions.get(f"shed_{slo_class}", 0)

    # -- rendering --------------------------------------------------------

    def describe(self) -> dict[str, Any]:
        """The compact health-detail form (rides probes, like the HBM
        headroom and SLO compliance)."""
        with self._lock:
            return {
                "level": self.level,
                "budget_factor": round(self.budget_factor, 6),
                "routable": self.level < MAX_LEVEL,
            }

    def snapshot(self) -> dict[str, Any]:
        """The full ``/debug/brownout`` form: ladder state, thresholds,
        last control inputs, per-action counters, projected recovery."""
        with self._lock:
            state = {
                "enabled": True,
                "level": self.level,
                "budget_factor": round(self.budget_factor, 6),
                "enter_burn": self.enter_burn,
                "exit_burn": self.exit_burn,
                "sustain_s": self.sustain_s,
                "exit_sustain_s": self.exit_sustain_s,
                "max_new_tokens": self.max_new_tokens,
                "aimd_cut": self.aimd_cut,
                "recover_per_s": self.recover_per_s,
                "min_headroom": self.min_headroom,
                "last_burn_5m": round(self._last_burn, 6),
                "last_headroom": (
                    None if self._last_headroom is None
                    else round(self._last_headroom, 6)
                ),
                "class_admit_fraction": dict(CLASS_ADMIT_FRACTION),
                "transitions": dict(self._transitions),
                "actions": dict(self._actions),
            }
        state["projected_recovery_s"] = round(self.projected_recovery_s(), 3)
        return state
