"""Shared token-stream shaping for the gRPC serving surfaces.

One place owns the streaming contract both the typed-protobuf and the
JSON gRPC servicers expose (and that must match the unary replies):

* cumulative decode so multi-byte UTF-8 never splits across chunks;
* stop sequences trimmed EXACTLY like the unary path (text held back
  until a match is ruled out);
* the engine's authoritative ``finish_reason`` on the final event;
* request cancellation on ANY abnormal consumer exit (client cancel,
  generator finalization, downstream error), so the KV slot frees
  instead of decoding for nobody.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, AsyncIterator


def normalize_stop(stop: Any) -> list[str]:
    """OpenAI-style ``stop`` forms: None/absent, one string, or a list."""
    if stop is None:
        return []
    if isinstance(stop, str):
        return [stop]
    return list(stop)


async def stream_generation(
    engine, prompt, kw: dict, tokenizer
) -> AsyncIterator[dict]:
    """Yield ``{"type": "piece", "token", "text"}`` events followed by one
    ``{"type": "done", "tokens", "ttft_ms", "finish_reason"}``.

    ``kw`` goes to ``engine.submit_generate`` verbatim — validation errors
    (prompt too long, top_p rejected, draining) raise out of the FIRST
    ``anext`` so callers can map them before any chunk is on the wire.
    """
    stops = normalize_stop(kw.get("stop"))
    req = engine.submit_generate(prompt, **kw)
    loop = asyncio.get_running_loop()
    # Monotonic: ttft/duration are INTERVALS — an NTP step between
    # submit and first token would skew (or negate) a wall-clock diff.
    start = time.monotonic()
    first_at = None
    n = 0
    hold = max((len(s) for s in stops), default=0)
    trimming = bool(stops) and tokenizer is not None
    ids: list[int] = []
    printed = ""
    finished = False
    try:
        while True:
            tok = await loop.run_in_executor(None, req.stream.get)
            if tok is None:
                break
            if first_at is None:
                first_at = time.monotonic()
            n += 1
            ids.append(tok)
            if tokenizer is None:
                yield {"type": "piece", "token": tok, "text": ""}
                continue
            full = tokenizer.decode(ids)
            if trimming:
                at = min(
                    (p for p in (full.find(s) for s in stops) if p != -1),
                    default=-1,
                )
                if at != -1:
                    full = full[:at]
                elif full.endswith("�"):
                    continue  # incomplete UTF-8 tail — hold back
                else:
                    full = full[: max(len(printed), len(full) - hold)]
            elif full.endswith("�"):
                continue
            if len(full) > len(printed):
                piece, printed = full[len(printed):], full
                yield {"type": "piece", "token": tok, "text": piece}
        result = req.future.result(timeout=30)  # authoritative reason
        finished = True
        yield {
            "type": "done",
            "tokens": n,
            "ttft_ms": round(
                ((first_at or time.monotonic()) - start) * 1e3, 3
            ),
            "finish_reason": result.finish_reason,
        }
    finally:
        if not finished:
            # Abnormal exit — cancel so the engine stops decoding for a
            # consumer that is gone (no-op on a completed future).
            # cancel_request also trips the request's CancelToken, which
            # the scheduler's lifecycle reap retires within one window.
            req.cancel_request()


async def stream_seq2seq(engine, prompt, tokenizer) -> AsyncIterator[dict]:
    """Stepped seq2seq streaming, shared by both gRPC surfaces (the same
    one-owner discipline as ``stream_generation`` — the chunking/ttft/
    decode logic must not drift between the JSON and typed servicers).

    Yields ``{"type": "piece", "token", "text"}`` per engine chunk, then
    ``{"type": "done", "tokens", "ttft_ms", "finish_reason"}``. Pieces
    use cumulative decode so multi-byte text never splits mid-chunk.
    """
    t0 = time.monotonic()  # interval math: immune to NTP wall steps
    all_ids: list[int] = []
    printed = ""
    ttft_ms = 0.0
    async for toks in engine.seq2seq_stream(prompt):
        if not all_ids:
            ttft_ms = round((time.monotonic() - t0) * 1e3, 2)
        all_ids.extend(toks)
        decoded = tokenizer.decode(all_ids) if tokenizer is not None else ""
        piece, printed = decoded[len(printed):], decoded
        yield {"type": "piece", "token": toks[0], "text": piece}
    yield {
        "type": "done",
        "tokens": len(all_ids),
        "ttft_ms": ttft_ms,
        "finish_reason": "stop",
    }
