"""Runtime multi-LoRA adapter lifecycle: load/unload into stacked
adapter slots with generation stamping so completions can never mix
weight sets. Mixin methods on InferenceEngine — split from
``engine.py`` (r4 VERDICT weak #10)."""

from __future__ import annotations



class LoRARuntimeMixin:
    """Adapter slot management (engine.load_lora / unload_lora)."""

    def _live_aid_requests(self, idx: int):
        """In-flight generate requests (decoding or prefilling) routed to
        adapter slot ``idx``."""
        # Snapshot both containers: the scheduler thread mutates them
        # concurrently (slot release, prefill finalize). Prefix-store
        # registrations are excluded — they carry their own staleness
        # contract (gen-stamp check at finalize resolves them to -1).
        reqs = [
            seq.request for seq in list(self._slots)
            if seq is not None and seq.request.aid == idx
            and not seq.request.prefix_store
        ]
        reqs += [
            st.request for st in list(self._prefilling.values())
            if st.request.aid == idx and not st.request.prefix_store
        ]
        return reqs

    def _fail_aid_requests(self, idx: int, why: str) -> None:
        """Fail in-flight requests routed to adapter slot ``idx``: a
        completion must never mix tokens from two different weight sets.
        The scheduler releases their KV slots at the next processed
        window (it treats a done future like a caller cancellation)."""
        for req in self._live_aid_requests(idx):
            if not req.future.done():
                req.future.set_exception(RuntimeError(why))
            req.stream.put(None)

    def load_lora(self, name: str, source) -> int:
        """Load a LoRA adapter into a free adapter slot under ``name``.

        source: an HF PEFT checkpoint dir (``adapter_config.json`` +
        safetensors) or a raw ``{target: (a [L, d_in, r], b [L, r,
        d_out])}`` dict. Re-loading an existing name overwrites its slot.
        Returns the adapter slot index (≥1). Safe while serving: leaf
        updates build new device arrays; in-flight windows keep the old
        tree, and the name routes to the slot only after the write lands.
        Requests still generating against the slot being overwritten
        (same-name reload, or a freed slot only dirty slots remain for)
        are FAILED rather than silently switched mid-completion; fresh
        loads prefer a free slot with no in-flight traffic.
        """
        if self.family != "llm":
            raise RuntimeError("LoRA adapters are for llm engines")
        if not self.lora_slots:
            raise RuntimeError(
                "engine compiled without adapter slots — set "
                "TPU_LORA_SLOTS>0"
            )
        from gofr_tpu.serving.lora import (
            load_peft_adapter,
            validate_adapter_leaves,
        )

        if isinstance(source, str):
            leaves = load_peft_adapter(
                source, self.cfg, self.lora_rank, self._lora_targets
            )
        else:
            leaves = dict(source)
            validate_adapter_leaves(
                leaves, self.cfg, self.lora_rank, self._lora_targets
            )
        idx = self._lora_names.get(name)
        if idx is None:
            used = set(self._lora_names.values())
            free = [
                i for i in range(1, self.lora_slots + 1) if i not in used
            ]
            if not free:
                raise RuntimeError(
                    f"all {self.lora_slots} adapter slots in use "
                    f"(TPU_LORA_SLOTS); unload_lora one first"
                )
            # Prefer a freed slot nothing is still generating against
            # (unloaded adapters let in-flight requests finish on base
            # weights); reuse a draining one only when forced to.
            idx = next(
                (i for i in free if not self._live_aid_requests(i)),
                free[0],
            )
        # Bump the generation FIRST: after this line the scheduler's
        # admission check rejects any queued request stamped under the
        # old weights, so the failure snapshot below cannot race one in
        # (bump-after-snapshot left a window where a request admitted
        # between the two escaped both checks and decoded under the new
        # adapter). The bump also invalidates pooled prefixes computed
        # under the previous occupant (reload keeps the same idx; a
        # fresh idx may still have stale entries from a late in-flight
        # store).
        self._lora_gen[idx] += 1
        # Overwriting a slot that live requests still route to would mix
        # two adapters inside single completions — fail them instead.
        self._fail_aid_requests(
            idx,
            f"LoRA adapter slot {idx} is being overwritten by a load of "
            f"{name!r} while this request was generating; resubmit",
        )
        if self._prefix_pool is not None:
            self._prefix_pool.purge_aid(idx)
        radix = getattr(self, "_radix", None)
        if radix is not None:
            # Same staleness rule for the automatic prefix cache: radix
            # entries hold K/V prefilled under the slot's previous
            # weights. Blocks still aliased by live tables survive until
            # those slots release (their requests fail above).
            radix.purge_aid(idx)
            self._publish_prefix_gauge()
        layers = dict(self.params["layers"])
        # Zero the WHOLE slot first: a reload with fewer targets than the
        # previous version must not leave the old version's deltas live.
        for t in self._lora_targets:
            if t in leaves:
                continue
            for suffix in ("_lora_a", "_lora_b"):
                leaf = layers[t + suffix]
                layers[t + suffix] = (
                    leaf.at[:, idx].set(self._jnp.zeros_like(leaf[:, idx]))
                )
        for t, (a, b) in leaves.items():
            dt = self.cfg.dtype
            layers[t + "_lora_a"] = (
                layers[t + "_lora_a"].at[:, idx].set(a.astype(dt))
            )
            layers[t + "_lora_b"] = (
                layers[t + "_lora_b"].at[:, idx].set(b.astype(dt))
            )
        self.params = {**self.params, "layers": layers}
        self._lora_names[name] = idx
        if self._logger is not None:
            self._logger.infof(
                "LoRA adapter %s loaded into slot %d (targets: %s)",
                name, idx, ",".join(sorted(leaves)),
            )
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_lora_adapters", float(len(self._lora_names)),
                "model", self.model_name,
            )
        return idx

    def unload_lora(self, name: str) -> None:
        """Zero ``name``'s adapter slot and free it. In-flight requests
        routed to the slot finish against the zeroed (= base) weights —
        callers should drain first if that matters."""
        idx = self._lora_names.pop(name, None)
        if idx is None:
            raise KeyError(f"no loaded LoRA adapter {name!r}")
        self._lora_gen[idx] += 1
        if self._prefix_pool is not None:
            # The adapter slot id may be reused by a later load; pooled
            # prefixes prefilled under it are stale the moment it frees.
            self._prefix_pool.purge_aid(idx)
        radix = getattr(self, "_radix", None)
        if radix is not None:
            radix.purge_aid(idx)
            self._publish_prefix_gauge()
        layers = dict(self.params["layers"])
        for t in self._lora_targets:
            for suffix in ("_lora_a", "_lora_b"):
                leaf = layers[t + suffix]
                layers[t + suffix] = (
                    leaf.at[:, idx].set(self._jnp.zeros_like(leaf[:, idx]))
                )
        self.params = {**self.params, "layers": layers}
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_lora_adapters", float(len(self._lora_names)),
                "model", self.model_name,
            )

    def lora_names(self) -> list[str]:
        """Loaded adapter names (OpenAI surface lists them as models)."""
        if self.family != "llm" or not getattr(self, "lora_slots", 0):
            return []
        return sorted(self._lora_names)

