"""Declarative serving SLOs and multi-window burn rates (ISSUE 12).

PR 6's phase histograms tell an operator what latency *was*; they do
not say whether the service is currently **breaking its promise** or
how fast it is spending its error budget. This module turns three
declarative objectives into that signal:

* ``TPU_SLO_TTFT_MS``   — a request is *good* when its time-to-first-
  token is at or under the threshold;
* ``TPU_SLO_E2E_MS``    — good when its end-to-end latency is at or
  under the threshold;
* ``TPU_SLO_AVAILABILITY`` — the compliance target (e.g. ``0.999``):
  for the ``availability`` SLO a request is good when it retired
  ``ok`` (sheds and errors are the server failing the client; client
  cancellations are excluded from the denominator). The same target is
  the latency SLOs' compliance fraction — one error budget discipline
  across all three (``0.99`` when unset but a latency SLO is).

**Per-tenant objectives** (ISSUE 13): ``TPU_SLO_TENANT_<NAME>_TTFT_MS``
/ ``_E2E_MS`` / ``_AVAILABILITY`` overrides give a tenant its own
thresholds on top of the global ones. The ``<NAME>`` env segment
matches the request's ``X-Tenant-Id`` case-insensitively (env keys are
conventionally upper-case). Per-tenant burn is exported as
``app_tpu_slo_tenant_burn_rate{tenant,slo,window}`` — the label set is
bounded by *configuration* (only tenants with an override export), and
the value still routes through the ``label_for``-style clamp discipline
(graftlint GL016). ``/debug/slo`` gains a per-tenant section.

**Burn rate** is the SRE-workbook form: over a window, the fraction of
bad requests divided by the error budget (``1 − target``). 1.0 means
the budget is being spent exactly as fast as it accrues; 10 means ten
times too fast. Evaluated over two windows — 5 minutes (page-fast) and
1 hour (sustained) — from bucketed ring counters, so memory is fixed
and old samples age out without timers. Exported as
``app_tpu_slo_burn_rate{slo,window}`` gauges plus an
``app_tpu_slo_compliant`` 0/1 gauge (every burn rate ≤ 1) that rides
health details and replica probes; the full state serves on
``/debug/slo``. The fast window is also the brownout controller's
control signal (``serving/brownout.py``: the runtime actuator this
module's gauges page on).

Observations arrive from the PR 6 phase records: the observability
hub's ``finalize`` feeds every retired timeline's outcome, phases, and
tenant here — request granularity, zero work on the dispatch path, and
the layer shares the flight recorder's off-switch semantics (no SLOs
configured → the engine holds no :class:`SLOEngine` at all).

Determinism: the clock is injectable and bucket boundaries are pure
arithmetic — tests state time instead of sleeping.
"""

from __future__ import annotations

import time

from typing import Any, Callable, Mapping, Optional

from gofr_tpu.analysis import lockcheck

#: (window label, window seconds, ring buckets) — 10 s buckets for the
#: fast window, 60 s for the sustained one.
WINDOWS: tuple[tuple[str, float, int], ...] = (
    ("5m", 300.0, 30),
    ("1h", 3600.0, 60),
)

#: Default compliance target when TPU_SLO_AVAILABILITY is unset but a
#: latency SLO is configured.
DEFAULT_TARGET = 0.99

#: The global objectives' scope key in the (scope, slo, window) counts
#: map — "" so it can never collide with a tenant id.
GLOBAL = ""


def tenant_objectives_from_config(config: Any) -> dict[str, dict[str, float]]:
    """Collect ``TPU_SLO_TENANT_<NAME>_{TTFT_MS,E2E_MS,AVAILABILITY}``
    overrides into ``{tenant: {field: value}}``. Keys are read from the
    process environment (the ``EnvLoader`` writes dotenv files there)
    plus a ``MockConfig``'s static map, so tests configure overrides
    the same way operators do. The ``<NAME>`` segment is lower-cased:
    tenant ids match case-insensitively."""
    import os

    keys: dict[str, str] = dict(os.environ)
    mock_values = getattr(config, "_values", None)
    if isinstance(mock_values, dict):
        keys.update(mock_values)
    prefix = "TPU_SLO_TENANT_"
    suffixes = (
        ("_TTFT_MS", "ttft_ms"),
        ("_E2E_MS", "e2e_ms"),
        ("_AVAILABILITY", "availability"),
    )
    out: dict[str, dict[str, float]] = {}
    for key, raw in keys.items():
        if not key.startswith(prefix):
            continue
        rest = key[len(prefix):]
        for suffix, field in suffixes:
            if rest.endswith(suffix) and len(rest) > len(suffix):
                name = rest[: -len(suffix)].lower()
                try:
                    value = float(raw)
                except (TypeError, ValueError):
                    break
                if value > 0:
                    out.setdefault(name, {})[field] = value
                break
    return out


class _Ring:
    """Good/total counts over a sliding window, in fixed buckets.

    ``observe`` lands in the bucket for ``now``; ``counts`` sums the
    buckets still inside the window. Stale buckets are lazily zeroed on
    first touch — no timers, O(buckets) worst case per read."""

    __slots__ = ("window_s", "bucket_s", "_good", "_total", "_stamp")

    def __init__(self, window_s: float, buckets: int) -> None:
        self.window_s = float(window_s)
        self.bucket_s = float(window_s) / buckets
        self._good = [0] * buckets
        self._total = [0] * buckets
        # Bucket epoch (``now // bucket_s``) each slot was last used
        # for; a mismatch means the slot's data is a lap old.
        self._stamp = [-1] * buckets

    def _slot(self, epoch: int) -> int:
        return epoch % len(self._total)

    def observe(self, now: float, good: bool) -> None:
        epoch = int(now / self.bucket_s)
        i = self._slot(epoch)
        if self._stamp[i] != epoch:
            self._stamp[i] = epoch
            self._good[i] = 0
            self._total[i] = 0
        self._total[i] += 1
        if good:
            self._good[i] += 1

    def counts(self, now: float) -> tuple[int, int]:
        """(good, total) over the buckets still inside the window."""
        epoch = int(now / self.bucket_s)
        lo = epoch - len(self._total) + 1
        good = total = 0
        for i, stamp in enumerate(self._stamp):
            if lo <= stamp <= epoch:
                good += self._good[i]
                total += self._total[i]
        return good, total


class _SLO:
    """One objective: a goodness predicate plus its per-window rings."""

    __slots__ = ("name", "threshold_ms", "rings")

    def __init__(self, name: str, threshold_ms: float) -> None:
        self.name = name
        self.threshold_ms = threshold_ms  # 0 for availability
        self.rings = {
            label: _Ring(seconds, buckets)
            for label, seconds, buckets in WINDOWS
        }


def _build_slos(
    ttft_ms: float, e2e_ms: float, availability: float
) -> dict[str, _SLO]:
    slos: dict[str, _SLO] = {}
    if ttft_ms > 0:
        slos["ttft"] = _SLO("ttft", float(ttft_ms))
    if e2e_ms > 0:
        slos["e2e"] = _SLO("e2e", float(e2e_ms))
    if availability > 0:
        slos["availability"] = _SLO("availability", 0.0)
    return slos


class SLOEngine:
    """Burn-rate evaluation over the configured objectives (see the
    module docstring). All mutation happens under one lock at request
    granularity — nothing here is on the dispatch path."""

    def __init__(
        self,
        model_name: str,
        *,
        ttft_ms: float = 0.0,
        e2e_ms: float = 0.0,
        availability: float = 0.0,
        tenant_objectives: Optional[
            Mapping[str, Mapping[str, float]]
        ] = None,
        track_tenants: int = 0,
        metrics: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.model_name = model_name
        self._metrics = metrics
        self._clock = clock
        self._lock = lockcheck.make_lock("SLOEngine._lock")
        self.target = (
            min(max(float(availability), 0.0), 0.9999999)
            if availability > 0 else DEFAULT_TARGET
        )
        self.error_budget = max(1e-7, 1.0 - self.target)
        self._slos: dict[str, _SLO] = _build_slos(
            ttft_ms, e2e_ms, availability
        )
        # Per-tenant overrides (ISSUE 13): each override tenant gets
        # its OWN ring set and error budget, evaluated from the same
        # retirement feed. Keys are lower-cased (case-insensitive
        # tenant match); the label set is configuration-bounded.
        self._tenant_slos: dict[str, dict[str, _SLO]] = {}
        self._tenant_budget: dict[str, float] = {}
        for name, spec in (tenant_objectives or {}).items():
            key = str(name).lower()
            slos = _build_slos(
                float(spec.get("ttft_ms", 0.0)),
                float(spec.get("e2e_ms", 0.0)),
                float(spec.get("availability", 0.0)),
            )
            if not slos:
                continue
            self._tenant_slos[key] = slos
            avail = float(spec.get("availability", 0.0))
            target = (
                min(max(avail, 0.0), 0.9999999) if avail > 0
                else self.target
            )
            self._tenant_budget[key] = max(1e-7, 1.0 - target)
        # Automatic per-tenant tracking (ISSUE 17): with
        # ``track_tenants > 0`` every observed tenant (up to the bound)
        # gets its own ring set judged against the GLOBAL objectives —
        # the control plane's per-tenant burn signal. Deliberately a
        # SEPARATE table from ``_tenant_slos``: these entries are
        # traffic-derived, so they never join the configuration-bounded
        # metric/debug label set (GL016).
        self.track_tenants = max(0, int(track_tenants))
        self._auto_slos: dict[str, dict[str, _SLO]] = {}
        self._ttft_ms = float(ttft_ms)
        self._e2e_ms = float(e2e_ms)
        self._availability = float(availability)
        # Cached GLOBAL compliance bit, refreshed by every
        # observation/health/describe pass (_publish_counts): the
        # routing hot path (ReplicaPool.pick via engine.slo_compliant)
        # reads THIS instead of rescanning every ring per request.
        self._last_compliant = True

    @property
    def enabled(self) -> bool:
        return bool(self._slos or self._tenant_slos)

    def _tenant_label(self, tenant: str) -> str:
        """Bounded label mapper (GL016 discipline): only tenants with a
        configured override ever reach this, so the label set is fixed
        at boot by configuration, not by request traffic."""
        return tenant

    # -- ingestion (request granularity, from the observability hub) ---

    @staticmethod
    def _judge(
        slos: dict[str, _SLO],
        outcome: str,
        phases: Mapping[str, float],
        t: float,
    ) -> None:
        """Land one retired request in one scope's rings (call under
        the lock). Latency SLOs only see requests that reached the
        phase (a shed never had a TTFT — availability is the SLO that
        charges it)."""
        slo = slos.get("ttft")
        if slo is not None and "ttft_s" in phases:
            good = phases["ttft_s"] * 1e3 <= slo.threshold_ms
            for ring in slo.rings.values():
                ring.observe(t, good)
        slo = slos.get("e2e")
        if slo is not None and "e2e_s" in phases:
            good = phases["e2e_s"] * 1e3 <= slo.threshold_ms
            for ring in slo.rings.values():
                ring.observe(t, good)
        slo = slos.get("availability")
        if slo is not None:
            for ring in slo.rings.values():
                ring.observe(t, outcome == "ok")

    def observe(
        self,
        outcome: str,
        phases: Mapping[str, float],
        now: Optional[float] = None,
        tenant: str = "",
    ) -> None:
        """One retired request: judge it against every configured SLO —
        the global objectives, plus the tenant's own when an override is
        configured for it. Cancelled requests are the client's choice
        and count nowhere."""
        if (not self._slos and not self._tenant_slos) or outcome == "cancelled":
            return
        t = self._clock() if now is None else now
        tkey = str(tenant or "").lower()
        with self._lock:
            self._judge(self._slos, outcome, phases, t)
            tslos = self._tenant_slos.get(tkey) if tkey else None
            if tslos is not None:
                self._judge(tslos, outcome, phases, t)
            if tkey and self.track_tenants > 0 and self._slos:
                auto = self._auto_slos.get(tkey)
                if auto is None:
                    if len(self._auto_slos) >= self.track_tenants:
                        self._evict_idle_auto(t)
                    if len(self._auto_slos) < self.track_tenants:
                        auto = self._auto_slos[tkey] = _build_slos(
                            self._ttft_ms,
                            self._e2e_ms,
                            self._availability,
                        )
                if auto is not None:
                    self._judge(auto, outcome, phases, t)
        self._publish(t)

    def _evict_idle_auto(self, now: float) -> None:
        """Drop auto-tracked tenants whose rings are all empty (call
        under the lock): the table stays bounded by ``track_tenants``
        without ever evicting a tenant that still has in-window data."""
        idle = [
            tenant for tenant, slos in self._auto_slos.items()
            if all(
                ring.counts(now)[1] == 0
                for obj in slos.values()
                for ring in obj.rings.values()
            )
        ]
        for tenant in idle:
            del self._auto_slos[tenant]

    # -- evaluation -----------------------------------------------------

    def _window_counts(
        self, now: float
    ) -> dict[tuple[str, str, str], tuple[int, int]]:
        """(scope, slo, window) → (good, total) for every ring — scope
        :data:`GLOBAL` for the global objectives, the tenant key for
        overrides — read under ONE lock pass: burn rates, compliance,
        gauges, and the debug snapshot all derive from this single read
        (no repeated ring scans contending with the retirement-path
        ``observe``)."""
        with self._lock:
            counts = {
                (GLOBAL, name, label): ring.counts(now)
                for name, obj in self._slos.items()
                for label, ring in obj.rings.items()
            }
            for tenant, slos in self._tenant_slos.items():
                for name, obj in slos.items():
                    for label, ring in obj.rings.items():
                        counts[(tenant, name, label)] = ring.counts(now)
            return counts

    def _budget_of(self, scope: str) -> float:
        if scope == GLOBAL:
            return self.error_budget
        return self._tenant_budget.get(scope, self.error_budget)

    def _burn(
        self, counts: tuple[int, int], scope: str = GLOBAL
    ) -> float:
        good, total = counts
        if total == 0:
            return 0.0  # an idle window burns nothing
        return ((total - good) / total) / self._budget_of(scope)

    def burn_rate(
        self,
        slo: str,
        window: str,
        now: Optional[float] = None,
        tenant: str = "",
    ) -> float:
        """Bad fraction over the window divided by the error budget;
        0.0 with no samples (an idle service burns nothing). With
        ``tenant``, reads that tenant's override rings."""
        t = self._clock() if now is None else now
        scope = str(tenant or "").lower() or GLOBAL
        with self._lock:
            slos = (
                self._slos if scope == GLOBAL
                else self._tenant_slos.get(scope, {})
            )
            obj = slos.get(slo)
            ring = obj.rings.get(window) if obj is not None else None
            if ring is None:
                return 0.0
            counts = ring.counts(t)
        return self._burn(counts, scope)

    def worst_burn(
        self, window: str = "5m", now: Optional[float] = None
    ) -> float:
        """The maximum GLOBAL burn rate over the window — the brownout
        controller's control signal (one locked read per scheduler
        pass; per-tenant overrides page, they don't brown the pod
        out)."""
        t = self._clock() if now is None else now
        with self._lock:
            counts = [
                obj.rings[window].counts(t)
                for obj in self._slos.values()
                if window in obj.rings
            ]
        if not counts:
            return 0.0
        return max(self._burn(c) for c in counts)

    def tenant_burns(
        self, window: str = "5m", now: Optional[float] = None
    ) -> dict[str, float]:
        """Per-tenant maximum burn over the window, from the
        auto-tracked rings (``track_tenants``) — the control plane's
        per-tenant brownout signal. Every tenant is judged against the
        GLOBAL objectives and budget, so the numbers are comparable
        across tenants; empty when tracking is off."""
        t = self._clock() if now is None else now
        with self._lock:
            per_tenant = {
                tenant: [
                    obj.rings[window].counts(t)
                    for obj in slos.values()
                    if window in obj.rings
                ]
                for tenant, slos in self._auto_slos.items()
            }
        return {
            tenant: max(self._burn(c) for c in counts)
            for tenant, counts in per_tenant.items()
            if counts
        }

    def compliant(self, now: Optional[float] = None) -> bool:
        """True while every GLOBAL (slo, window) burn rate is ≤ 1 —
        spending the error budget no faster than it accrues. Tenant
        overrides alert per tenant but do not flip the replica-level
        routing bit."""
        t = self._clock() if now is None else now
        return all(
            self._burn(c) <= 1.0
            for (scope, _, _), c in self._window_counts(t).items()
            if scope == GLOBAL
        )

    def compliant_cached(self) -> bool:
        """The compliance bit as of the last observation or
        health/describe pass — an O(1) read for the per-request routing
        path. Staleness is bounded by traffic and probe cadence (both
        refresh it); use :meth:`compliant` for an exact read."""
        return self._last_compliant

    def _publish_counts(
        self, counts: dict[tuple[str, str, str], tuple[int, int]]
    ) -> bool:
        """Refresh the burn-rate and compliance gauges from one counts
        read; returns the GLOBAL compliance bit. Called on every
        observation AND every health/describe/snapshot read, so
        recovery (an empty window) reaches Prometheus through the
        periodic health probes even when no new request arrives to
        trigger it."""
        burns = {
            key: self._burn(c, key[0]) for key, c in counts.items()
        }
        ok = all(
            b <= 1.0 for (scope, _, _), b in burns.items()
            if scope == GLOBAL
        )
        self._last_compliant = ok
        if self._metrics is not None:
            for (scope, name, label), burn in burns.items():
                if scope == GLOBAL:
                    self._metrics.set_gauge(
                        "app_tpu_slo_burn_rate", round(burn, 6),
                        "model", self.model_name,
                        "slo", name, "window", label,
                    )
                else:
                    self._metrics.set_gauge(
                        "app_tpu_slo_tenant_burn_rate", round(burn, 6),
                        "model", self.model_name,
                        "tenant", self._tenant_label(scope),
                        "slo", name, "window", label,
                    )
            self._metrics.set_gauge(
                "app_tpu_slo_compliant", 1.0 if ok else 0.0,
                "model", self.model_name,
            )
        return ok

    def _publish(self, now: float) -> None:
        self._publish_counts(self._window_counts(now))

    # -- rendering -------------------------------------------------------

    def _scope_section(
        self,
        scope: str,
        slos: dict[str, _SLO],
        counts: dict[tuple[str, str, str], tuple[int, int]],
    ) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for name, obj in slos.items():
            windows: dict[str, Any] = {}
            for label, seconds, _ in WINDOWS:
                good, total = counts[(scope, name, label)]
                windows[label] = {
                    "window_s": seconds,
                    "good": good,
                    "total": total,
                    "burn_rate": round(
                        self._burn((good, total), scope), 6
                    ),
                }
            out[name] = {
                "threshold_ms": obj.threshold_ms,
                "target": round(1.0 - self._budget_of(scope), 7),
                "windows": windows,
            }
        return out

    def snapshot(self) -> dict[str, Any]:
        """The ``/debug/slo`` form: objective, target, and per-window
        burn state for every configured SLO — global plus the
        per-tenant override section. One ring read serves the snapshot
        AND refreshes the gauges."""
        t = self._clock()
        counts = self._window_counts(t)
        ok = self._publish_counts(counts)
        out: dict[str, Any] = {
            "enabled": True,
            "target": self.target,
            "error_budget": round(self.error_budget, 7),
            "compliant": ok,
            "slos": self._scope_section(GLOBAL, self._slos, counts),
        }
        if self._tenant_slos:
            out["tenants"] = {
                tenant: self._scope_section(tenant, slos, counts)
                for tenant, slos in self._tenant_slos.items()
            }
        return out

    def describe(self) -> dict[str, Any]:
        """The compact health-detail form (rides probes): compliance
        plus the fast window's burn per SLO. Health checks and pool
        probes call this periodically, so it also refreshes the gauges
        — alerts keyed on ``app_tpu_slo_*`` recover when the windows
        empty, not only when the next request arrives."""
        t = self._clock()
        counts = self._window_counts(t)
        ok = self._publish_counts(counts)
        out: dict[str, Any] = {
            "compliant": ok,
            "target": self.target,
            "burn_rate_5m": {
                name: round(self._burn(counts[(GLOBAL, name, "5m")]), 6)
                for name in self._slos
            },
        }
        if self._tenant_slos:
            out["tenants"] = {
                tenant: {
                    "compliant": all(
                        self._burn(counts[(tenant, name, label)], tenant)
                        <= 1.0
                        for name in slos
                        for label, _, _ in WINDOWS
                    ),
                }
                for tenant, slos in self._tenant_slos.items()
            }
        return out
