"""Declarative serving SLOs and multi-window burn rates (ISSUE 12).

PR 6's phase histograms tell an operator what latency *was*; they do
not say whether the service is currently **breaking its promise** or
how fast it is spending its error budget. This module turns three
declarative objectives into that signal:

* ``TPU_SLO_TTFT_MS``   — a request is *good* when its time-to-first-
  token is at or under the threshold;
* ``TPU_SLO_E2E_MS``    — good when its end-to-end latency is at or
  under the threshold;
* ``TPU_SLO_AVAILABILITY`` — the compliance target (e.g. ``0.999``):
  for the ``availability`` SLO a request is good when it retired
  ``ok`` (sheds and errors are the server failing the client; client
  cancellations are excluded from the denominator). The same target is
  the latency SLOs' compliance fraction — one error budget discipline
  across all three (``0.99`` when unset but a latency SLO is).

**Burn rate** is the SRE-workbook form: over a window, the fraction of
bad requests divided by the error budget (``1 − target``). 1.0 means
the budget is being spent exactly as fast as it accrues; 10 means ten
times too fast. Evaluated over two windows — 5 minutes (page-fast) and
1 hour (sustained) — from bucketed ring counters, so memory is fixed
and old samples age out without timers. Exported as
``app_tpu_slo_burn_rate{slo,window}`` gauges plus an
``app_tpu_slo_compliant`` 0/1 gauge (every burn rate ≤ 1) that rides
health details and replica probes; the full state serves on
``/debug/slo``.

Observations arrive from the PR 6 phase records: the observability
hub's ``finalize`` feeds every retired timeline's outcome and phases
here — request granularity, zero work on the dispatch path, and the
layer shares the flight recorder's off-switch semantics (no SLOs
configured → the engine holds no :class:`SLOEngine` at all).

Determinism: the clock is injectable and bucket boundaries are pure
arithmetic — tests state time instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Mapping, Optional

#: (window label, window seconds, ring buckets) — 10 s buckets for the
#: fast window, 60 s for the sustained one.
WINDOWS: tuple[tuple[str, float, int], ...] = (
    ("5m", 300.0, 30),
    ("1h", 3600.0, 60),
)

#: Default compliance target when TPU_SLO_AVAILABILITY is unset but a
#: latency SLO is configured.
DEFAULT_TARGET = 0.99


class _Ring:
    """Good/total counts over a sliding window, in fixed buckets.

    ``observe`` lands in the bucket for ``now``; ``counts`` sums the
    buckets still inside the window. Stale buckets are lazily zeroed on
    first touch — no timers, O(buckets) worst case per read."""

    __slots__ = ("window_s", "bucket_s", "_good", "_total", "_stamp")

    def __init__(self, window_s: float, buckets: int) -> None:
        self.window_s = float(window_s)
        self.bucket_s = float(window_s) / buckets
        self._good = [0] * buckets
        self._total = [0] * buckets
        # Bucket epoch (``now // bucket_s``) each slot was last used
        # for; a mismatch means the slot's data is a lap old.
        self._stamp = [-1] * buckets

    def _slot(self, epoch: int) -> int:
        return epoch % len(self._total)

    def observe(self, now: float, good: bool) -> None:
        epoch = int(now / self.bucket_s)
        i = self._slot(epoch)
        if self._stamp[i] != epoch:
            self._stamp[i] = epoch
            self._good[i] = 0
            self._total[i] = 0
        self._total[i] += 1
        if good:
            self._good[i] += 1

    def counts(self, now: float) -> tuple[int, int]:
        """(good, total) over the buckets still inside the window."""
        epoch = int(now / self.bucket_s)
        lo = epoch - len(self._total) + 1
        good = total = 0
        for i, stamp in enumerate(self._stamp):
            if lo <= stamp <= epoch:
                good += self._good[i]
                total += self._total[i]
        return good, total


class _SLO:
    """One objective: a goodness predicate plus its per-window rings."""

    __slots__ = ("name", "threshold_ms", "rings")

    def __init__(self, name: str, threshold_ms: float) -> None:
        self.name = name
        self.threshold_ms = threshold_ms  # 0 for availability
        self.rings = {
            label: _Ring(seconds, buckets)
            for label, seconds, buckets in WINDOWS
        }


class SLOEngine:
    """Burn-rate evaluation over the configured objectives (see the
    module docstring). All mutation happens under one lock at request
    granularity — nothing here is on the dispatch path."""

    def __init__(
        self,
        model_name: str,
        *,
        ttft_ms: float = 0.0,
        e2e_ms: float = 0.0,
        availability: float = 0.0,
        metrics: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.model_name = model_name
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self.target = (
            min(max(float(availability), 0.0), 0.9999999)
            if availability > 0 else DEFAULT_TARGET
        )
        self.error_budget = max(1e-7, 1.0 - self.target)
        self._slos: dict[str, _SLO] = {}
        if ttft_ms > 0:
            self._slos["ttft"] = _SLO("ttft", float(ttft_ms))
        if e2e_ms > 0:
            self._slos["e2e"] = _SLO("e2e", float(e2e_ms))
        if availability > 0:
            self._slos["availability"] = _SLO("availability", 0.0)

    @property
    def enabled(self) -> bool:
        return bool(self._slos)

    # -- ingestion (request granularity, from the observability hub) ---

    def observe(
        self,
        outcome: str,
        phases: Mapping[str, float],
        now: Optional[float] = None,
    ) -> None:
        """One retired request: judge it against every configured SLO.
        Latency SLOs only see requests that reached the phase (a shed
        never had a TTFT — availability is the SLO that charges it);
        cancelled requests are the client's choice and count nowhere."""
        if not self._slos or outcome == "cancelled":
            return
        t = self._clock() if now is None else now
        with self._lock:
            slo = self._slos.get("ttft")
            if slo is not None and "ttft_s" in phases:
                good = phases["ttft_s"] * 1e3 <= slo.threshold_ms
                for ring in slo.rings.values():
                    ring.observe(t, good)
            slo = self._slos.get("e2e")
            if slo is not None and "e2e_s" in phases:
                good = phases["e2e_s"] * 1e3 <= slo.threshold_ms
                for ring in slo.rings.values():
                    ring.observe(t, good)
            slo = self._slos.get("availability")
            if slo is not None:
                for ring in slo.rings.values():
                    ring.observe(t, outcome == "ok")
        self._publish(t)

    # -- evaluation -----------------------------------------------------

    def _window_counts(
        self, now: float
    ) -> dict[tuple[str, str], tuple[int, int]]:
        """(slo, window) → (good, total) for every ring, read under ONE
        lock pass — burn rates, compliance, gauges, and the debug
        snapshot all derive from this single read (no repeated ring
        scans contending with the retirement-path ``observe``)."""
        with self._lock:
            return {
                (name, label): ring.counts(now)
                for name, obj in self._slos.items()
                for label, ring in obj.rings.items()
            }

    def _burn(self, counts: tuple[int, int]) -> float:
        good, total = counts
        if total == 0:
            return 0.0  # an idle window burns nothing
        return ((total - good) / total) / self.error_budget

    def burn_rate(
        self, slo: str, window: str, now: Optional[float] = None
    ) -> float:
        """Bad fraction over the window divided by the error budget;
        0.0 with no samples (an idle service burns nothing)."""
        t = self._clock() if now is None else now
        with self._lock:
            obj = self._slos.get(slo)
            ring = obj.rings.get(window) if obj is not None else None
            if ring is None:
                return 0.0
            counts = ring.counts(t)
        return self._burn(counts)

    def compliant(self, now: Optional[float] = None) -> bool:
        """True while every (slo, window) burn rate is ≤ 1 — spending
        the error budget no faster than it accrues."""
        t = self._clock() if now is None else now
        return all(
            self._burn(c) <= 1.0
            for c in self._window_counts(t).values()
        )

    def _publish_counts(
        self, counts: dict[tuple[str, str], tuple[int, int]]
    ) -> bool:
        """Refresh the burn-rate and compliance gauges from one counts
        read; returns the compliance bit. Called on every observation
        AND every health/describe/snapshot read, so recovery (an empty
        window) reaches Prometheus through the periodic health probes
        even when no new request arrives to trigger it."""
        burns = {key: self._burn(c) for key, c in counts.items()}
        ok = all(b <= 1.0 for b in burns.values())
        if self._metrics is not None:
            for (name, label), burn in burns.items():
                self._metrics.set_gauge(
                    "app_tpu_slo_burn_rate", round(burn, 6),
                    "model", self.model_name,
                    "slo", name, "window", label,
                )
            self._metrics.set_gauge(
                "app_tpu_slo_compliant", 1.0 if ok else 0.0,
                "model", self.model_name,
            )
        return ok

    def _publish(self, now: float) -> None:
        self._publish_counts(self._window_counts(now))

    # -- rendering -------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The ``/debug/slo`` form: objective, target, and per-window
        burn state for every configured SLO. One ring read serves the
        snapshot AND refreshes the gauges."""
        t = self._clock()
        counts = self._window_counts(t)
        ok = self._publish_counts(counts)
        slos: dict[str, Any] = {}
        for name, obj in self._slos.items():
            windows: dict[str, Any] = {}
            for label, seconds, _ in WINDOWS:
                good, total = counts[(name, label)]
                windows[label] = {
                    "window_s": seconds,
                    "good": good,
                    "total": total,
                    "burn_rate": round(
                        self._burn((good, total)), 6
                    ),
                }
            slos[name] = {
                "threshold_ms": obj.threshold_ms,
                "target": self.target,
                "windows": windows,
            }
        return {
            "enabled": True,
            "target": self.target,
            "error_budget": round(self.error_budget, 7),
            "compliant": ok,
            "slos": slos,
        }

    def describe(self) -> dict[str, Any]:
        """The compact health-detail form (rides probes): compliance
        plus the fast window's burn per SLO. Health checks and pool
        probes call this periodically, so it also refreshes the gauges
        — alerts keyed on ``app_tpu_slo_*`` recover when the windows
        empty, not only when the next request arrives."""
        t = self._clock()
        counts = self._window_counts(t)
        ok = self._publish_counts(counts)
        return {
            "compliant": ok,
            "target": self.target,
            "burn_rate_5m": {
                name: round(self._burn(counts[(name, "5m")]), 6)
                for name in self._slos
            },
        }
